"""Checkpoint / resume for distributed domain state (orbax-backed).

The reference has no true checkpointing — its nearest features are the
ParaView CSV dumps (reference: src/stencil.cu:1188-1264) and astaroth's
``AC_start_step`` config knob that the mini-app never restores
(reference: astaroth/astaroth.conf:36-38). SURVEY.md section 5.4 calls for
real checkpoint/restore as the modern equivalent; this module provides
it: sharded field arrays are written with orbax (each host writes its
own shards; restore re-shards onto the current mesh), alongside a JSON
metadata record (step counter, grid geometry) used to validate
compatibility on resume.

Robustness (the resilience subsystem's storage layer):

* one ``CheckpointManager`` is cached per directory — the save loop of
  a long campaign reuses it instead of paying construct/close churn on
  every checkpoint; :func:`close_checkpoints` (also an atexit hook)
  releases them.
* every array carries a sha256 digest in the meta record; restore
  verifies it, so a bit-flipped or truncated checkpoint is detected
  rather than silently resumed from.
* :func:`restore_domain` is fallback-aware: when the newest step is
  corrupt or unreadable it logs a warning and walks back to the next
  older step, raising only when NO step is restorable.
* orbax save/restore I/O runs through :func:`..utils.retry.retry` so a
  transient filesystem error costs a backoff, not the run.
"""

from __future__ import annotations

import atexit
import hashlib
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .logging import LOG_WARN
from .retry import retry


class CorruptCheckpointError(RuntimeError):
    """A checkpoint step exists but cannot be trusted (orbax restore
    failure or integrity-digest mismatch)."""


# ----------------------------------------------------------------------
# namespace-component hygiene (multi-tenant checkpoint trees)
# ----------------------------------------------------------------------
def validate_checkpoint_component(component: str,
                                  kind: str = "component") -> str:
    """Validate a string that will become ONE directory component of a
    checkpoint namespace (a tenant or campaign id in the serving
    layer). Tenant ids come from untrusted requests; an id like
    ``../other-tenant`` must never escape its namespace. Rejects empty
    strings, path separators (``/`` and ``\\``), the traversal names
    ``.`` / ``..``, NUL, and other control characters. Returns the
    component unchanged when valid; raises ``ValueError`` otherwise."""
    if not isinstance(component, str) or not component:
        raise ValueError(f"{kind} must be a non-empty string, "
                         f"got {component!r}")
    if component in (".", ".."):
        raise ValueError(f"{kind} {component!r} is a path traversal "
                         f"name")
    bad = [ch for ch in component
           if ch in ("/", "\\", "\x00") or ord(ch) < 0x20]
    if bad:
        raise ValueError(f"{kind} {component!r} contains path "
                         f"separators or control characters {bad!r}")
    return component


# ----------------------------------------------------------------------
# manager cache: one CheckpointManager per directory
# ----------------------------------------------------------------------
# directory (absolute) -> (manager, max_to_keep it was built with)
_MANAGERS: Dict[str, Tuple[Any, Optional[int]]] = {}
_atexit_registered = False

#: read-only callers (latest_step/restore/meta probes) don't care about
#: retention — they reuse any cached manager. Writers pass the real
#: max_to_keep, where ``None`` genuinely means "keep every step".
_ANY_RETENTION = object()


def _manager(directory: str, max_to_keep=_ANY_RETENTION):
    """The cached manager for ``directory`` (built on first use; rebuilt
    when the caller's ``max_to_keep`` differs from the one it was built
    with). Callers must NOT close it — :func:`close_checkpoints` owns
    the lifecycle."""
    global _atexit_registered
    import orbax.checkpoint as ocp
    key = str(Path(directory).absolute())
    cached = _MANAGERS.get(key)
    if cached is not None:
        mgr, kept = cached
        if max_to_keep is _ANY_RETENTION or kept == max_to_keep:
            return mgr
        _close_one(key)
    keep = None if max_to_keep is _ANY_RETENTION else max_to_keep
    opts = ocp.CheckpointManagerOptions(max_to_keep=keep, create=True)
    mgr = ocp.CheckpointManager(key, options=opts)
    _MANAGERS[key] = (mgr, keep)
    if not _atexit_registered:
        atexit.register(close_checkpoints)
        _atexit_registered = True
    return mgr


def _close_one(key: str) -> None:
    mgr, _ = _MANAGERS.pop(key)
    try:
        mgr.close()
    except Exception as e:  # noqa: BLE001 - the dir may be gone (tmpdirs)
        LOG_WARN(f"closing checkpoint manager for {key}: "
                 f"{type(e).__name__}: {e}")


def close_checkpoints(directory: Optional[str] = None) -> None:
    """Close the cached manager for ``directory`` (or ALL cached
    managers when None). Safe to call repeatedly; also runs atexit."""
    if directory is not None:
        key = str(Path(directory).absolute())
        if key in _MANAGERS:
            _close_one(key)
        return
    for key in list(_MANAGERS):
        _close_one(key)


# ----------------------------------------------------------------------
# array integrity digests
# ----------------------------------------------------------------------
def _single_host() -> bool:
    """Integrity digests need every array fully addressable from this
    process — true only for single-host runs (patchable in tests)."""
    return jax.process_count() == 1


def array_digest(arr) -> str:
    """sha256 over an array's raw bytes + shape + dtype (host order) —
    the integrity record written next to every checkpointed array."""
    import numpy as np
    host = np.asarray(arr)
    h = hashlib.sha256()
    h.update(str(host.shape).encode())
    h.update(str(host.dtype).encode())
    h.update(np.ascontiguousarray(host).tobytes())
    return h.hexdigest()


def verify_digests(arrays: Dict[str, jnp.ndarray],
                   digests: Dict[str, str]) -> List[str]:
    """Names whose current digest does not match the recorded one
    (restored-but-tampered data). Arrays without a recorded digest
    (older checkpoints) are skipped — absence is not corruption."""
    bad = []
    for name, arr in arrays.items():
        want = digests.get(name)
        if want is not None and array_digest(arr) != want:
            bad.append(name)
    return sorted(bad)


# ----------------------------------------------------------------------
# low-level save/restore
# ----------------------------------------------------------------------
def save_state(directory: str, step: int, arrays: Dict[str, jnp.ndarray],
               meta: Optional[Dict[str, Any]] = None,
               max_to_keep: Optional[int] = None,
               attempts: int = 3, base_delay: float = 0.1,
               sleep=None) -> None:
    """Write ``arrays`` (a flat dict of possibly-sharded jax arrays) and
    JSON-serializable ``meta`` as checkpoint ``step``. Transient
    ``OSError``s are retried with backoff (``attempts``/``base_delay``/
    ``sleep`` — callers owning their own retry loop, like the
    resilience driver, pass ``attempts=1`` so exactly one layer
    retries)."""
    import orbax.checkpoint as ocp
    mgr = _manager(directory, max_to_keep)

    def attempt():
        # a rolled-back run re-checkpoints steps it already saved once
        # (possibly as a corrupt/partial write) — replace, don't refuse
        # (read=True: see the directory as it is, not the cached
        # manager's construction-time snapshot)
        if step in mgr.all_steps(read=True):
            try:
                mgr.delete(step)
            except Exception:  # noqa: BLE001 - partial step dirs
                import shutil
                shutil.rmtree(Path(directory).absolute() / str(step),
                              ignore_errors=True)
        mgr.save(step, args=ocp.args.Composite(
            state=ocp.args.StandardSave(arrays),
            meta=ocp.args.JsonSave(meta or {})), force=True)
        mgr.wait_until_finished()

    retry(attempt, attempts=attempts, base_delay=base_delay, sleep=sleep)


def latest_step(directory: str) -> Optional[int]:
    steps = all_steps(directory)
    return steps[-1] if steps else None


def all_steps(directory: str) -> List[int]:
    """Every checkpoint step in ``directory``, ascending. Always reads
    the directory fresh (``read=True``) — the cached manager's
    in-memory step list is a construction-time snapshot and would be
    blind to steps another process wrote (a monitor polling a
    campaign's checkpoint dir must see them)."""
    return sorted(_manager(directory).all_steps(read=True))


def restore_state(directory: str,
                  targets: Dict[str, jax.ShapeDtypeStruct],
                  step: Optional[int] = None
                  ) -> Tuple[int, Dict[str, jnp.ndarray], Dict[str, Any]]:
    """Restore arrays onto the shardings given in ``targets`` (a dict of
    ``jax.ShapeDtypeStruct`` with ``.sharding`` set — restoring onto a
    different mesh than the one that saved is supported, orbax reshards).
    Returns ``(step, arrays, meta)``."""
    import orbax.checkpoint as ocp
    mgr = _manager(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    out = retry(lambda: mgr.restore(step, args=ocp.args.Composite(
        state=ocp.args.StandardRestore(targets),
        meta=ocp.args.JsonRestore())), attempts=3, base_delay=0.1)
    return step, dict(out["state"]), dict(out["meta"] or {})


# ----------------------------------------------------------------------
# DistributedDomain integration
# ----------------------------------------------------------------------
def _interior_fns(dd):
    """Jitted global-padded <-> global-interior converters (device-side,
    stay sharded): checkpoints are mesh-independent so they can be
    restored onto a different decomposition. Cached on the domain so
    periodic checkpoints don't retrace/recompile every save."""
    cached = getattr(dd, "_ckpt_interior_fns", None)
    if cached is not None:
        return cached
    from jax import lax
    from jax.sharding import PartitionSpec as P

    # allocation pads, not the stencil radius: temporal blocking
    # (set_exchange_every) deepens the buffers to s*r per side
    lo = dd.alloc_radius.pad_lo()
    hi = dd.alloc_radius.pad_hi()
    local = dd.local_size
    spec = P("z", "y", "x")

    def extract_shard(p):
        return lax.slice(p, (lo.z, lo.y, lo.x),
                         (lo.z + local.z, lo.y + local.y, lo.x + local.x))

    def insert_shard(interior):
        padded = jnp.zeros((local.z + lo.z + hi.z, local.y + lo.y + hi.y,
                            local.x + lo.x + hi.x), dtype=interior.dtype)
        return lax.dynamic_update_slice(padded, interior,
                                        (lo.z, lo.y, lo.x))

    fns = tuple(
        jax.jit(jax.shard_map(f, mesh=dd.mesh, in_specs=spec,
                              out_specs=spec, check_vma=False))
        for f in (extract_shard, insert_shard))
    dd._ckpt_interior_fns = fns
    return fns


def domain_meta(dd) -> Dict[str, Any]:
    return {
        "size": list(dd.size),
        "mesh": list(dd.placement.dim()),
        "quantities": list(dd._names),
        "dtypes": {q: str(dd._dtypes[q]) for q in dd._names},
    }


_warned_multihost_integrity = False


def _track_dir(dd, directory: str) -> None:
    """Remember the directories this domain checkpoints into so
    ``DistributedDomain.close_checkpoints()`` can release exactly its
    own managers."""
    dirs = getattr(dd, "_ckpt_dirs", None)
    if dirs is None:
        dirs = set()
        dd._ckpt_dirs = dirs
    dirs.add(str(Path(directory).absolute()))


def save_domain(dd, directory: str, step: int,
                extra: Optional[Dict[str, jnp.ndarray]] = None,
                max_to_keep: Optional[int] = None,
                meta_extra: Optional[Dict[str, Any]] = None,
                integrity: bool = True,
                attempts: int = 3, base_delay: float = 0.1,
                sleep=None,
                fields: Optional[Dict[str, jnp.ndarray]] = None) -> None:
    """Checkpoint a DistributedDomain's curr fields (+ optional extra
    arrays, e.g. RK accumulators) at ``step``. ``meta_extra`` is merged
    into the JSON meta record (the resilience driver tags preemption
    checkpoints through it); ``integrity=True`` (default) records a
    sha256 per array so restore can detect corruption — it costs one
    host gather per array per checkpoint. ``fields`` overrides the
    source arrays (same padded-global layout as ``curr``) — the async
    megastep offload saves from device COPIES taken at the segment
    boundary, so the live buffers can be donated to the next segment
    while orbax drains the copies."""
    from ..geometry import Dim3
    _track_dir(dd, directory)
    src = dd.curr if fields is None else fields
    if dd.rem == Dim3(0, 0, 0):
        extract, _ = _interior_fns(dd)
        arrays = {q: extract(src[q]) for q in dd._names}
    else:
        # uneven shards: per-shard interior extents differ, so the
        # device-side uniform extraction would embed dead rows; gather
        # the true dd.size interior on host instead (slower, correct)
        arrays = {q: jnp.asarray(dd.assemble_interior(np.asarray(src[q])))
                  for q in dd._names}
    meta = domain_meta(dd)
    meta["extra"] = {}
    for k, v in (extra or {}).items():
        arrays[f"extra:{k}"] = v
        meta["extra"][k] = {"shape": list(v.shape), "dtype": str(v.dtype)}
    if integrity and not _single_host():
        # digesting needs the full array on THIS host; multi-host
        # shards are not process-addressable, so integrity is skipped
        # (restore treats absent digests as not-corrupt, never flags)
        global _warned_multihost_integrity
        if not _warned_multihost_integrity:
            _warned_multihost_integrity = True
            LOG_WARN("checkpoint integrity digests are single-host "
                     "only; skipping them on this multi-host run")
        integrity = False
    if integrity:
        meta["integrity"] = {k: array_digest(v) for k, v in arrays.items()}
    for k, v in (meta_extra or {}).items():
        meta[k] = v
    save_state(directory, step, arrays, meta=meta,
               max_to_keep=max_to_keep, attempts=attempts,
               base_delay=base_delay, sleep=sleep)


def _restore_step_arrays(dd, mgr, step: int
                         ) -> Tuple[Dict[str, jnp.ndarray],
                                    Dict[str, Any]]:
    """Restore checkpoint ``step`` for ``dd`` and verify integrity.
    Raises :class:`CorruptCheckpointError` when the step cannot be
    trusted, or ``ValueError`` when it belongs to a DIFFERENT problem
    (size/quantities/dtype mismatch — not corruption, never fallback)."""
    import orbax.checkpoint as ocp
    from ..geometry import Dim3
    from ..local_domain import zyx_shape
    from jax.sharding import NamedSharding, PartitionSpec as P

    # the meta probe: transient OSErrors get the same backoff as the
    # bulk restore below; a step whose meta record STILL cannot be
    # read is corrupt
    try:
        probe = retry(lambda: mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())),
            attempts=3, base_delay=0.1)
        saved_meta = dict(probe["meta"] or {})
    except Exception as e:  # noqa: BLE001 - orbax raises many types
        raise CorruptCheckpointError(
            f"step {step}: meta record unreadable "
            f"({type(e).__name__}: {e})") from e

    # compatibility gates come from the meta record, BEFORE the bulk
    # restore: a mismatched domain raises (the caller's bug), it is not
    # a corrupt checkpoint to skip past
    if saved_meta.get("size") and list(dd.size) != saved_meta["size"]:
        raise ValueError(f"checkpoint size {saved_meta['size']} != "
                         f"domain {list(dd.size)}")
    if saved_meta.get("quantities") and \
            saved_meta["quantities"] != list(dd._names):
        raise ValueError(f"checkpoint quantities "
                         f"{saved_meta['quantities']} != "
                         f"{list(dd._names)}")
    for q, dt in (saved_meta.get("dtypes") or {}).items():
        if q in dd._dtypes and str(dd._dtypes[q]) != dt:
            raise ValueError(f"checkpoint dtype {dt} for {q!r} != "
                             f"domain dtype {dd._dtypes[q]}")

    targets: Dict[str, jax.ShapeDtypeStruct] = {}
    ishape = zyx_shape(dd.size)
    uneven = dd.rem != Dim3(0, 0, 0)
    # even: interior globals shard P(z,y,x); uneven: dd.size doesn't
    # divide the mesh, restore replicated and re-scatter via set_interior
    repl = NamedSharding(dd.mesh, P())
    for q in dd._names:
        cur = dd.curr[q]
        targets[q] = jax.ShapeDtypeStruct(
            ishape, cur.dtype, sharding=repl if uneven else cur.sharding)
    cur0 = dd.curr[dd._names[0]]
    for k, desc in (saved_meta.get("extra") or {}).items():
        shape = tuple(desc["shape"])
        # field-shaped extras (the RK accumulators) restore onto the
        # field sharding; anything else (the PIC particle lanes are 1D
        # SoA arrays) restores REPLICATED and the owner re-shards — a
        # 3D PartitionSpec cannot shard a 1D array
        sh = cur0.sharding if len(shape) == cur0.ndim else repl
        targets[f"extra:{k}"] = jax.ShapeDtypeStruct(
            shape, jnp.dtype(desc["dtype"]), sharding=sh)
    try:
        # the meta record was already read by the probe above — only
        # the state item rides this bulk restore
        out = retry(lambda: mgr.restore(step, args=ocp.args.Composite(
            state=ocp.args.StandardRestore(targets))),
            attempts=3, base_delay=0.1)
    except Exception as e:  # noqa: BLE001 - truncated files raise deep
        raise CorruptCheckpointError(
            f"step {step}: restore failed "
            f"({type(e).__name__}: {e})") from e
    arrays = dict(out["state"])
    if _single_host():  # digests need host-addressable arrays
        bad = verify_digests(arrays, saved_meta.get("integrity") or {})
        if bad:
            raise CorruptCheckpointError(
                f"step {step}: integrity sha256 mismatch for {bad} "
                f"(bit-rot or tampering)")
    return arrays, saved_meta


def restore_domain(dd, directory: str, step: Optional[int] = None
                   ) -> Tuple[int, Dict[str, jnp.ndarray]]:
    """Restore a realized DistributedDomain's curr fields in place;
    returns ``(step, extra_arrays)``. The domain must have the same
    global size and quantities as the checkpoint (mesh may differ —
    orbax reshards onto the current one).

    Fallback-aware: when the requested/newest step is corrupt or
    unreadable (integrity mismatch, truncated file, orbax error) a
    warning is logged and the next-older step is tried; the call raises
    only when NO step is restorable (or on a genuine domain mismatch,
    which no amount of walking back would fix)."""
    from ..geometry import Dim3
    _track_dir(dd, directory)
    mgr = _manager(directory)
    if step is not None:
        candidates = [step]
    else:
        candidates = sorted(all_steps(directory), reverse=True)
        if not candidates:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    arrays = saved_meta = step_found = None
    last_err: Optional[CorruptCheckpointError] = None
    for cand in candidates:
        try:
            arrays, saved_meta = _restore_step_arrays(dd, mgr, cand)
            step_found = cand
            break
        except CorruptCheckpointError as e:
            last_err = e
            LOG_WARN(f"checkpoint {directory} {e}; "
                     f"falling back to an older step")
    if step_found is None:
        raise CorruptCheckpointError(
            f"no restorable checkpoint in {directory} "
            f"(tried steps {candidates}): {last_err}")

    if dd.rem == Dim3(0, 0, 0):
        _, insert = _interior_fns(dd)
        for q in dd._names:
            dd.curr[q] = insert(arrays[q])
    else:
        import numpy as np
        for q in dd._names:
            dd.set_interior(q, np.asarray(arrays[q]))
    # halos are zero after insert; one exchange makes the state whole
    dd.exchange()
    extra = {k[len("extra:"):]: v for k, v in arrays.items()
             if k.startswith("extra:")}
    return step_found, extra


def checkpoint_meta(directory: str, step: Optional[int] = None
                    ) -> Dict[str, Any]:
    """The JSON meta record of checkpoint ``step`` (latest when None) —
    the resilience driver reads the ``preempted`` tag through this
    without paying an array restore."""
    import orbax.checkpoint as ocp
    mgr = _manager(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
    probe = mgr.restore(
        step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
    return dict(probe["meta"] or {})
