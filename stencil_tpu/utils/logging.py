"""Leveled stderr logging with process/device prefix.

The analog of the reference's compile-time-leveled macros
(reference: include/stencil/logging.hpp:12-53): level selected by the
``STENCIL_TPU_LOG`` env var (spew|debug|info|warn|error|fatal, default
info); messages are prefixed with the jax process index the way the
reference prefixes the MPI rank. LOG_FATAL raises instead of exit(1) —
fail-fast, but catchable.
"""

from __future__ import annotations

import os
import sys

_LEVELS = {"spew": 0, "debug": 1, "info": 2, "warn": 3, "error": 4, "fatal": 5}
_level = _LEVELS.get(os.environ.get("STENCIL_TPU_LOG", "info").lower(), 2)


def _rank() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def _emit(tag: str, lvl: int, msg: str) -> None:
    if lvl >= _level:
        print(f"[{_rank()}] {tag}: {msg}", file=sys.stderr)


def LOG_SPEW(msg: str) -> None:
    _emit("SPEW", 0, msg)


def LOG_DEBUG(msg: str) -> None:
    _emit("DEBUG", 1, msg)


def LOG_INFO(msg: str) -> None:
    _emit("INFO", 2, msg)


def LOG_WARN(msg: str) -> None:
    _emit("WARN", 3, msg)


def LOG_ERROR(msg: str) -> None:
    _emit("ERROR", 4, msg)


class FatalError(RuntimeError):
    pass


def LOG_FATAL(msg: str) -> None:
    _emit("FATAL", 5, msg)
    raise FatalError(msg)


def set_level(name: str) -> None:
    global _level
    _level = _LEVELS[name.lower()]
