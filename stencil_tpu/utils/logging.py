"""Leveled stderr logging with process/device prefix.

The analog of the reference's compile-time-leveled macros
(reference: include/stencil/logging.hpp:12-53): level selected by the
``STENCIL_TPU_LOG`` env var (spew|debug|info|warn|error|fatal, default
info); messages are prefixed with the jax process index the way the
reference prefixes the MPI rank. LOG_FATAL raises instead of exit(1) —
fail-fast, but catchable.

Format selected by ``STENCIL_TPU_LOG_FORMAT`` (alias
``STENCIL_LOG_FORMAT``): ``text`` (default, unchanged) or ``json`` —
each record routed through the unified telemetry event schema
(:mod:`..telemetry.events`: run id, monotonic seq, schema version) and
printed as one JSON line to stderr, so fleet log scrapers read logs
and service/resilience event streams in ONE format:
``{"event": "log", "time": ..., "run": ..., "seq": ..., "schema": 1,
"level": "info", "rank": 0, "message": ...}``.
"""

from __future__ import annotations

import os
import sys
import threading

_LEVELS = {"spew": 0, "debug": 1, "info": 2, "warn": 3, "error": 4, "fatal": 5}
_level = _LEVELS.get(os.environ.get("STENCIL_TPU_LOG", "info").lower(), 2)

_FORMATS = ("text", "json")
_format = (os.environ.get("STENCIL_TPU_LOG_FORMAT")
           or os.environ.get("STENCIL_LOG_FORMAT", "text")).lower()
if _format not in _FORMATS:
    _format = "text"

#: lazily-built process-wide EventLog for json-format records (one run
#: id, one monotonic sequence for every LOG_* line this process emits;
#: the lock keeps first-use races from minting two run ids)
_json_log = None
_json_log_lock = threading.Lock()


def _rank() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def _emit_json(tag: str, msg: str) -> None:
    global _json_log
    log = _json_log
    if log is None:
        with _json_log_lock:
            if _json_log is None:
                from ..telemetry.events import EventLog, StreamJsonSink
                _json_log = EventLog(sinks=(StreamJsonSink(),))
            log = _json_log
    log.emit("log", level=tag.lower(), rank=_rank(), message=msg)


def _emit(tag: str, lvl: int, msg: str) -> None:
    if lvl >= _level:
        if _format == "json":
            _emit_json(tag, msg)
        else:
            print(f"[{_rank()}] {tag}: {msg}", file=sys.stderr)


def LOG_SPEW(msg: str) -> None:
    _emit("SPEW", 0, msg)


def LOG_DEBUG(msg: str) -> None:
    _emit("DEBUG", 1, msg)


def LOG_INFO(msg: str) -> None:
    _emit("INFO", 2, msg)


def LOG_WARN(msg: str) -> None:
    _emit("WARN", 3, msg)


def LOG_ERROR(msg: str) -> None:
    _emit("ERROR", 4, msg)


class FatalError(RuntimeError):
    pass


def LOG_FATAL(msg: str) -> None:
    _emit("FATAL", 5, msg)
    raise FatalError(msg)


def set_level(name: str) -> None:
    global _level
    _level = _LEVELS[name.lower()]


def set_format(name: str) -> None:
    """Switch the record format at runtime (``text`` | ``json``)."""
    global _format
    name = name.lower()
    if name not in _FORMATS:
        raise ValueError(f"log format must be one of {_FORMATS}, "
                         f"got {name!r}")
    _format = name
