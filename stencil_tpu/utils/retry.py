"""Bounded retry with exponential backoff for transient I/O.

Long campaigns die to transient filesystem hiccups (an NFS blip during
an orbax save, a contended rename on the tuning plan cache) far more
often than to real corruption. Every orbax save/restore and the plan
cache's store/load run through :func:`retry` so a transient ``OSError``
costs a short backoff instead of the job; persistent failures still
raise the last error after the attempt budget is spent.

The clock is injectable (``sleep=``) so recovery timing is unit-tested
with a fake clock, and ``on_retry`` lets callers (the resilience
driver's event log) record every retried failure.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


def retry(fn: Callable[[], T], attempts: int = 3,
          base_delay: float = 0.1,
          retriable: Tuple[Type[BaseException], ...] = (OSError,),
          sleep: Optional[Callable[[float], None]] = None,
          on_retry: Optional[Callable[[int, BaseException, float],
                                      None]] = None) -> T:
    """Call ``fn`` up to ``attempts`` times, sleeping
    ``base_delay * 2**k`` after the k-th failure (exponential backoff).

    Only exceptions matching ``retriable`` are retried — anything else
    propagates immediately (a dtype mismatch is not transient). The
    final failure re-raises the last error. ``on_retry(attempt, exc,
    delay)`` is invoked before each backoff sleep.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    if sleep is None:
        sleep = time.sleep
    for k in range(attempts):
        try:
            return fn()
        except retriable as e:  # noqa: PERF203 - retry loop by design
            if k == attempts - 1:
                raise
            delay = base_delay * (2 ** k)
            if on_retry is not None:
                on_retry(k + 1, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
