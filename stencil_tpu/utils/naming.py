"""Name matching for the CLIs: fnmatch with literal-bracket tolerance.

Registry targets, tuner plan keys, and bench ids embed literal
brackets (``analysis.tiling.jacobi_halo[512]``,
``models.jacobi.step_n[xla-temporal[s=1.1.4]]``), which collide with
fnmatch's character-class syntax — ``*[s=2]`` parses ``[s=2]`` as a
character class and never matches the literal name. ``glob_match``
tries the raw pattern first (so old ``?512?`` spellings keep working)
and then a variant with every ``[`` escaped to the ``[[]`` character
class, so ``--only 'analysis.schedule.*[k=4]'`` and
``gate --bench 'bench_exchange*'`` just work. The one matcher is
shared by the analysis and observatory CLIs so bracket handling can
never drift between them.
"""

import fnmatch

__all__ = ["glob_match"]


def glob_match(name: str, pattern: str) -> bool:
    """True when ``name`` matches ``pattern`` as a glob, treating
    ``[`` in the pattern as a literal bracket when the raw fnmatch
    reading fails. An exact string match always passes."""
    if name == pattern:
        return True
    if fnmatch.fnmatchcase(name, pattern):
        return True
    if "[" in pattern:
        return fnmatch.fnmatchcase(name, pattern.replace("[", "[[]"))
    return False
