"""Logical 3D lattice of subdomain indices with periodic neighbors.

TPU-native re-implementation of the reference's Topology
(reference: include/stencil/topology.hpp:9-30, src/topology.cpp:5-17).
The reference only implements PERIODIC boundaries (NONE is fatal); we
support both PERIODIC and NONE (neighbor may not exist).
"""

from __future__ import annotations

import enum
from typing import NamedTuple

from .geometry import Dim3, Dim3Like


class Boundary(enum.Enum):
    """Boundary condition for the global lattice (reference:
    include/stencil/boundary.hpp — dead code there; live here)."""

    PERIODIC = "periodic"
    NONE = "none"


class OptionalNeighbor(NamedTuple):
    exists: bool
    index: Dim3


class Topology:
    """3D lattice of subdomain indices (reference: topology.hpp:9-30)."""

    def __init__(self, dim: Dim3Like, boundary: Boundary = Boundary.PERIODIC) -> None:
        self.dim = Dim3.of(dim)
        self.boundary = boundary

    def get_neighbor(self, index: Dim3Like, dir: Dim3Like) -> OptionalNeighbor:
        """Neighbor of ``index`` in direction ``dir``; wraps periodically
        (reference: src/topology.cpp:5-17)."""
        index = Dim3.of(index)
        dir = Dim3.of(dir)
        raw = index + dir
        if self.boundary == Boundary.PERIODIC:
            return OptionalNeighbor(True, raw.wrap(self.dim))
        inside = (0 <= raw.x < self.dim.x and 0 <= raw.y < self.dim.y
                  and 0 <= raw.z < self.dim.z)
        return OptionalNeighbor(inside, raw.wrap(self.dim) if inside else raw)
