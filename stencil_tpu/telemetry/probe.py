"""In-graph step metrics: counters that ride the health probe.

The resilient run loop already pays for exactly ONE small all-reduce
per probe (``resilience/health.py``, pinned at the StableHLO level).
Telemetry must not add a second one — TEMPI-style (arXiv:2012.14363),
it interposes on the communication that already exists instead of
issuing its own. :class:`StepMetrics` packs cheap on-device counters
into extra columns of the probe's stacked stats vector, so the one
existing all-reduce carries them for free:

* ``substeps``   — cumulative member steps completed at probe time;
* ``wire_bytes`` — cumulative exchanged wire bytes, priced by the same
  calibrated byte model the static analyzer cross-checks EXACTLY
  against lowered HLO (``analysis/costmodel.py``), amortized across
  temporal blocking — so "bytes on the wire so far" is the HLO-exact
  figure, not an estimate.

Proven contracts (``telemetry.*`` stencil-lint registry targets):
the instrumented probe still lowers to exactly 1 all_reduce; the
instrumented PRODUCTION Jacobi step still lowers to 6 collective
permutes + exactly 1 all_reduce; and its exchange bytes still match
the analytic model exactly — instrumentation adds zero collectives and
zero wire bytes. ``tests/fixtures/lint/bad_probe_metrics.py`` is the
negative control (a metrics probe that pays its own all-reduce).

Values travel as f32 (the probe vector's dtype): exact up to 2**24,
documented rounding beyond — fine for smoke-scale counters; fleet
dashboards track rates, not 53-bit totals.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import numpy as np

#: the step-metric columns, in probe-vector order
STEP_METRIC_NAMES: Tuple[str, ...] = ("substeps", "wire_bytes")


class StepMetrics:
    """The on-device counter block for one realized domain.

    Plug into :class:`~stencil_tpu.resilience.health.HealthSentinel`
    via its ``metrics=`` argument: the sentinel appends
    ``values(step)`` as extra probe columns and decodes them back into
    ``HealthStats.metrics``.

    Counters are keyed to the campaign position ``step``: wire bytes
    for steps up to ``base_step`` were priced at the configuration(s)
    in force when they ran (carried in ``base_bytes``); steps beyond it
    are priced at this domain's current per-step figure. A mid-run
    reconfiguration (degradation ladder) must hand the old counter to
    :meth:`rebased` so the new price applies only to future steps —
    never retroactively. Steps re-executed after a rollback are not
    double-counted by design: the counter tracks campaign progress,
    not dispatch count."""

    names: Tuple[str, ...] = STEP_METRIC_NAMES

    def __init__(self, dd, base_step: int = 0,
                 base_bytes: float = 0.0) -> None:
        #: whole-mesh modeled wire bytes per STEP (amortized across
        #: temporal blocking) — the figure the costmodel checker
        #: proves equals the lowered HLO's bytes
        self.bytes_per_step = float(dd.exchange_bytes_amortized_per_step())
        self.base_step = int(base_step)
        self.base_bytes = float(base_bytes)
        # the domain's mesh, so values() can commit the vector
        # replicated (a single-device put would reshard implicitly at
        # dispatch — disallowed under the hot-loop transfer guard)
        self._mesh = getattr(dd, "mesh", None)

    def cumulative_bytes(self, step: int) -> float:
        """Modeled wire bytes for the campaign's first ``step`` steps."""
        return self.base_bytes + \
            max(0, int(step) - self.base_step) * self.bytes_per_step

    def rebased(self, dd, step: int) -> "StepMetrics":
        """The counter block for a reconfigured domain, carrying the
        bytes already accounted at ``step`` so the new configuration's
        price applies only from here on."""
        return StepMetrics(dd, base_step=step,
                           base_bytes=self.cumulative_bytes(step))

    def host_values(self, step: int) -> np.ndarray:
        """The f32 metrics vector for a probe of ``step``, on host —
        callers that dispatch under the hot-loop transfer guard
        device_put it explicitly (``megastep.metric_base_vec``)."""
        step = int(step)
        return np.asarray([float(step), self.cumulative_bytes(step)],
                          dtype=np.float32)

    def values(self, step: int):
        """The metrics vector as a replicated device array; the
        transfer is EXPLICIT (``jax.device_put`` with the domain's
        mesh sharding) so guarded hot loops stay clean — no implicit
        dispatch-time reshard."""
        import jax

        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(self.host_values(step),
                                  NamedSharding(self._mesh, P()))
        return jax.device_put(self.host_values(step))

    def decode(self, metrics: Dict[str, float]) -> Dict[str, float]:
        """Derived figures from harvested probe metrics: the raw
        cumulative counters plus the amortized B/step they imply (the
        model-vs-probe agreement the run-loop metrics export)."""
        out = dict(metrics)
        steps = out.get("substeps", 0.0)
        out["bytes_per_step_probe"] = (out.get("wire_bytes", 0.0) / steps
                                       if steps else 0.0)
        out["bytes_per_step_model"] = self.bytes_per_step
        return out


def step_metrics_for(dd):
    """A :class:`StepMetrics` for ``dd``, or None when the domain has
    no exchange byte model to ride (never raises — telemetry must not
    take down the loop it observes)."""
    try:
        return StepMetrics(dd)
    except Exception:  # noqa: BLE001 - absent model/engine -> no metrics
        return None
