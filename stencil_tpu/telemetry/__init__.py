"""Unified telemetry: spans, in-graph step metrics, and a metrics
surface.

The ROADMAP north star is serving heavy traffic; you cannot operate a
fleet you cannot see. This package makes observability a subsystem
instead of a convention, in three layers that share one identity (run
id, span id, schema version):

* **Structured spans** (:mod:`.spans`) — a thread-safe tracer whose
  spans (campaign -> segment -> exchange/compute/checkpoint/tune) are
  simultaneously ``jax.named_scope`` + ``TraceAnnotation`` ranges
  (correlating with XLA profiler output, via
  ``utils/profiling.scope``) and exportable records, dumped as Chrome
  trace-event JSON for Perfetto.

* **In-graph step metrics** (:mod:`.probe`) — cheap on-device counters
  (sub-steps, model-exact wire bytes) that ride the health sentinel's
  ONE existing all-reduce; the ``telemetry.*`` stencil-lint registry
  targets prove the instrumented production step adds zero collectives
  and zero wire bytes.

* **Metrics registry** (:mod:`.metrics`, :mod:`.http`) — labeled
  counters/gauges/histograms with Prometheus text exposition and JSON
  snapshots; ``CampaignService.metrics_text()``, the stdlib
  ``/metrics`` endpoint (``apps/serve.py --metrics-port``), and the
  ``python -m stencil_tpu.telemetry`` CLI are the surfaces.

* **One event schema** (:mod:`.events`) — the resilience driver and
  the campaign service emit through the same versioned
  :class:`EventLog` (run id, monotonic seq, span id) with pluggable
  sinks: bounded in-memory ring, JSONL file, caller-owned list.

Metric names, labels, and the event schema version are a stable
contract — see README "Observability".
"""

from .events import (EVENT_SCHEMA_VERSION, EventLog, JsonlSink,
                     ListSink, RingSink, StreamJsonSink, new_run_id,
                     validate_events)
from .http import MetricsServer
from .metrics import (DEFAULT_BUCKETS, METRICS_SCHEMA_VERSION, Counter,
                      Gauge, Histogram, MetricsRegistry, get_registry,
                      metric_value, parse_prometheus_text,
                      render_snapshot_text, snapshot_value)
from .probe import STEP_METRIC_NAMES, StepMetrics, step_metrics_for
from .spans import (Span, Tracer, get_tracer, set_tracer,
                    validate_chrome_trace)

__all__ = [
    "EVENT_SCHEMA_VERSION", "METRICS_SCHEMA_VERSION",
    "EventLog", "ListSink", "RingSink", "JsonlSink", "StreamJsonSink",
    "new_run_id", "validate_events",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "DEFAULT_BUCKETS", "metric_value", "parse_prometheus_text",
    "snapshot_value", "render_snapshot_text",
    "MetricsServer",
    "Span", "Tracer", "get_tracer", "set_tracer",
    "validate_chrome_trace",
    "STEP_METRIC_NAMES", "StepMetrics", "step_metrics_for",
]
