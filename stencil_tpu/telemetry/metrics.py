"""Counters / gauges / histograms with a Prometheus text surface.

The operational metrics layer: labeled counters, gauges, and
histograms in one thread-safe :class:`MetricsRegistry`, exportable two
ways — Prometheus text exposition (``to_prometheus_text``, what the
``/metrics`` endpoint and ``CampaignService.metrics_text()`` serve)
and a JSON snapshot (``snapshot``, the CI artifact and the
``python -m stencil_tpu.telemetry`` input).

Metric names and labels are a stable contract (documented in README
"Observability"); tests and the CI gates assert the serving warm-path
invariants from this exported surface rather than internal fields —
:func:`metric_value` / :func:`snapshot_value` are the tiny accessors
they use, so the asserted artifact is exactly what an external scraper
sees.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

#: snapshot schema version (bump on breaking key changes)
METRICS_SCHEMA_VERSION = 1

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label(v: str) -> str:
    """Escape a label value per exposition format 0.0.4 (backslash,
    double-quote, newline) — tenant-controlled strings must not be able
    to corrupt the scrape."""
    return (v.replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _label_text(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


class _Metric:
    """Shared plumbing: a name, help text, and per-label-set values."""

    kind = ""

    def __init__(self, name: str, help: str, lock: threading.RLock
                 ) -> None:
        self.name = name
        self.help = help
        self._lock = lock
        self._values: Dict[LabelKey, float] = {}

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def _samples(self) -> List[Dict]:
        return [{"labels": dict(k), "value": v}
                for k, v in sorted(self._values.items())]


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _render_metric(out: List[str], name: str, kind: str, help: str,
                   samples) -> None:
    """Render one metric's HELP/TYPE header and samples (the JSON
    snapshot sample shape) as exposition text — the ONE place the
    line format lives, shared by the live scrape and the snapshot
    CLI so the two surfaces cannot drift."""
    if help:
        # HELP escapes backslash + newline (format 0.0.4) — a wrapped
        # help string must not corrupt the scrape
        esc = help.replace("\\", r"\\").replace("\n", r"\n")
        out.append(f"# HELP {name} {esc}")
    out.append(f"# TYPE {name} {kind}")
    for s in samples:
        key = _label_key(s.get("labels") or {})
        if kind == "histogram":
            for le, n in (s.get("buckets") or {}).items():
                lk = key + (("le", le),)
                out.append(f"{name}_bucket{_label_text(lk)} {n}")
            lk = key + (("le", "+Inf"),)
            out.append(f"{name}_bucket{_label_text(lk)} "
                       f"{s.get('count', 0)}")
            out.append(f"{name}_sum{_label_text(key)} "
                       f"{_format_value(s.get('sum', 0.0))}")
            out.append(f"{name}_count{_label_text(key)} "
                       f"{s.get('count', 0)}")
        else:
            out.append(f"{name}{_label_text(key)} "
                       f"{_format_value(s.get('value', 0.0))}")


class Counter(_Metric):
    """Monotonically increasing count (``*_total`` by convention)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Metric):
    """A value that goes up and down (queue depth, steps/s)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


#: latency-flavored default buckets (seconds)
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   60.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each
    ``le``-bucket counts observations <= its bound, plus ``+Inf``)."""

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.RLock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, help, lock)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"histogram {name} needs >= 1 bucket")
        self.buckets: Tuple[float, ...] = tuple(bs)
        # per label set: [bucket counts..., +Inf count], sum
        self._hist: Dict[LabelKey, Tuple[List[int], float]] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            counts, total = self._hist.get(
                key, ([0] * (len(self.buckets) + 1), 0.0))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1
            self._hist[key] = (counts, total + float(value))

    def value(self, **labels) -> float:
        raise TypeError(
            f"histogram {self.name} has no single value; use "
            f"count()/sum() or the *_bucket/_sum/_count series")

    def count(self, **labels) -> int:
        with self._lock:
            got = self._hist.get(_label_key(labels))
            return got[0][-1] if got else 0

    def sum(self, **labels) -> float:
        with self._lock:
            got = self._hist.get(_label_key(labels))
            return got[1] if got else 0.0

    def _samples(self) -> List[Dict]:
        out = []
        for k, (counts, total) in sorted(self._hist.items()):
            out.append({"labels": dict(k), "count": counts[-1],
                        "sum": total,
                        "buckets": {_format_value(b): counts[i]
                                    for i, b in enumerate(self.buckets)}})
        return out


class MetricsRegistry:
    """Thread-safe home of every metric one process/service exports.

    Registration is idempotent by name (re-registering returns the
    existing metric; a kind mismatch raises), so instrumentation code
    can declare metrics where it uses them."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            got = self._metrics.get(name)
            if got is not None:
                if not isinstance(got, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{got.kind}, not {cls.kind}")
                want = kw.get("buckets")
                if want is not None and tuple(
                        sorted(float(b) for b in want)) != got.buckets:
                    # silently keeping the first buckets would bin the
                    # caller's observations into bounds it never chose
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {got.buckets}, not {tuple(want)}")
                return got
            m = cls(name, help, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)  # type: ignore

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)  # type: ignore

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None
                  ) -> Histogram:
        """``buckets=None`` means no preference: creation uses
        :data:`DEFAULT_BUCKETS`, and re-declaring an existing histogram
        without buckets stays idempotent even when its first
        registration chose custom bounds (only an EXPLICIT conflicting
        choice raises)."""
        kw = {} if buckets is None else {"buckets": buckets}
        return self._register(Histogram, name, help, **kw)  # type: ignore

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    # -- export surfaces ------------------------------------------------
    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                _render_metric(out, name, m.kind, m.help, m._samples())
        return "\n".join(out) + "\n"

    def snapshot(self) -> Dict:
        """JSON-serializable snapshot (the CI artifact format)."""
        with self._lock:
            metrics = {
                name: {"type": m.kind, "help": m.help,
                       "samples": m._samples()}
                for name, m in sorted(self._metrics.items())}
        return {"schema": METRICS_SCHEMA_VERSION, "time": time.time(),
                "metrics": metrics}

    def write_snapshot(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.snapshot(), f, indent=1)


#: the process-default registry (run loops; services own their own)
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY


# ---------------------------------------------------------------------------
# accessors for exported surfaces (tests / CI assert through these, so
# the asserted artifact is exactly the external one)


_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')


def _unescape_label(v: str) -> str:
    return re.sub(r"\\(.)",
                  lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), v)


def parse_prometheus_text(text: str) -> Dict[str, Dict[LabelKey, float]]:
    """Parse exposition text back to {name: {label key: value}}.
    Label values are unescaped per format 0.0.4, so values containing
    quotes, commas, or backslashes round-trip exactly."""
    out: Dict[str, Dict[LabelKey, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        if not head:
            continue
        if "{" in head:
            name, _, rest = head.partition("{")
            labels = {k: _unescape_label(v)
                      for k, v in _LABEL_RE.findall(rest)}
            key = _label_key(labels)
        else:
            name, key = head, ()
        out.setdefault(name, {})[key] = (
            math.inf if val == "+Inf" else float(val))
    return out


def metric_value(text_or_parsed: Union[str, Dict], name: str,
                 **labels) -> float:
    """The value of ``name{labels}`` in exposition text (missing ->
    0.0, the Prometheus absent-series convention)."""
    parsed = (parse_prometheus_text(text_or_parsed)
              if isinstance(text_or_parsed, str) else text_or_parsed)
    return parsed.get(name, {}).get(_label_key(labels), 0.0)


def snapshot_value(snap: Dict, name: str, **labels) -> float:
    """The value of ``name{labels}`` in a :meth:`MetricsRegistry.
    snapshot` payload (missing -> 0.0)."""
    want = _label_key(labels)
    metric = (snap.get("metrics") or {}).get(name)
    if not metric:
        return 0.0
    for sample in metric.get("samples", ()):
        if _label_key(sample.get("labels") or {}) == want:
            return float(sample.get("value",
                                    sample.get("count", 0.0)))
    return 0.0


def render_snapshot_text(snap: Dict) -> str:
    """Re-render a JSON snapshot as Prometheus text (the
    ``python -m stencil_tpu.telemetry snapshot`` output) — same
    renderer as the live scrape (:func:`_render_metric`)."""
    out: List[str] = []
    for name, m in sorted((snap.get("metrics") or {}).items()):
        _render_metric(out, name, m.get("type", ""), m.get("help", ""),
                       m.get("samples", ()))
    return "\n".join(out) + "\n"
