"""Structured spans: one tree, three audiences.

A telemetry span (campaign -> segment -> exchange/compute/checkpoint/
tune) is simultaneously:

* a ``jax.named_scope`` — ops traced inside it carry the span name
  into the XLA metadata, so the span tree lines up with compiled-op
  names in an XLA profile;
* a ``jax.profiler.TraceAnnotation`` — the host wall-time range shows
  on the profiler timeline (the NVTX-range analog the reference library
  puts on every stream);
* an exportable record with a stable id (``<run>/<n>``), parent id,
  begin/end timestamps, and attributes — dumped as Chrome trace-event
  JSON (:meth:`Tracer.export_chrome_trace`) loadable in Perfetto or
  ``chrome://tracing``, no profiler session required.

The first two come from wrapping :func:`..utils.profiling.scope`
(which the repo already used ad hoc); the third is what was missing —
an in-process record a service can export per run.

:class:`Tracer` is thread-safe: each thread keeps its own span stack
(``threading.local``), finished spans land in one bounded ring.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Sequence

from ..utils.profiling import scope
from .events import new_run_id


@dataclasses.dataclass
class Span:
    """One finished (or live) span."""

    name: str
    span_id: str
    parent_id: Optional[str]
    start_s: float          # seconds since the tracer's epoch
    end_s: Optional[float] = None
    thread: int = 0
    attrs: Dict = dataclasses.field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return (self.end_s - self.start_s) if self.end_s is not None \
            else 0.0


class Tracer:
    """Thread-safe in-process span recorder with Perfetto export."""

    #: export identity keys — span attrs may not shadow them (the
    #: same contract as ``EventLog.RESERVED``)
    RESERVED = frozenset(("span_id", "parent_id"))

    def __init__(self, run_id: Optional[str] = None,
                 capacity: int = 65536) -> None:
        self.run_id = run_id or new_run_id()
        self._lock = threading.Lock()
        self._counter = 0
        self._local = threading.local()
        self._finished: deque = deque(maxlen=int(capacity))
        self._dropped = 0
        self._epoch = time.perf_counter()
        #: wall-clock time of the epoch (Perfetto metadata)
        self.epoch_unix = time.time()

    # -- recording ------------------------------------------------------
    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _new_id(self) -> str:
        with self._lock:
            n = self._counter
            self._counter += 1
        return f"{self.run_id}/{n}"

    def current_span_id(self) -> Optional[str]:
        st = self._stack()
        return st[-1].span_id if st else None

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        """Open a child span of the thread's current span. Inside the
        block, traced ops get the span name as a ``named_scope`` and
        host time shows as a ``TraceAnnotation`` (via
        ``utils.profiling.scope``)."""
        bad = self.RESERVED.intersection(attrs)
        if bad:
            raise ValueError(
                f"span attrs may not shadow identity keys: {sorted(bad)}")
        st = self._stack()
        sp = Span(name=name, span_id=self._new_id(),
                  parent_id=st[-1].span_id if st else None,
                  start_s=time.perf_counter() - self._epoch,
                  thread=threading.get_ident(), attrs=dict(attrs))
        st.append(sp)
        try:
            with scope(name):
                yield sp
        finally:
            sp.end_s = time.perf_counter() - self._epoch
            st.pop()
            with self._lock:
                if len(self._finished) == self._finished.maxlen:
                    self._dropped += 1
                self._finished.append(sp)

    def finished(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    @property
    def dropped(self) -> int:
        """Finished spans evicted from the ring — truncation is never
        silent (exported parent ids may reference evicted spans)."""
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            self._dropped = 0

    # -- export ---------------------------------------------------------
    def chrome_trace(self) -> Dict:
        """The Chrome trace-event payload (``ph: "X"`` complete events,
        microsecond timestamps) Perfetto and chrome://tracing load."""
        events = []
        pid = os.getpid()
        for sp in self.finished():
            args = {"span_id": sp.span_id}
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            args.update({k: v for k, v in sp.attrs.items()
                         if isinstance(v, (str, int, float, bool))
                         or v is None})
            events.append({
                "name": sp.name, "cat": "stencil_tpu", "ph": "X",
                "ts": round(sp.start_s * 1e6, 3),
                "dur": round(sp.duration_s * 1e6, 3),
                "pid": pid, "tid": sp.thread, "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"run": self.run_id,
                              "epoch_unix_s": self.epoch_unix,
                              "dropped_spans": self.dropped,
                              "tool": "stencil_tpu.telemetry"}}

    def export_chrome_trace(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(), f, indent=1)


def validate_chrome_trace(payload) -> List[str]:
    """Structural validation against the trace-event format (the CI
    gate for exported traces). Accepts the payload dict or a path.
    Returns human-readable problems (empty = loads in Perfetto)."""
    problems: List[str] = []
    if isinstance(payload, (str, os.PathLike)):
        try:
            with open(payload, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            return [f"cannot load trace: {type(e).__name__}: {e}"]
    if not isinstance(payload, dict):
        return ["top level must be a JSON object"]
    events = payload.get("traceEvents")
    if not isinstance(events, list):
        return ["missing traceEvents array"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        if not isinstance(ev.get("name"), str):
            problems.append(f"event {i}: missing name")
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            problems.append(f"event {i}: missing ph")
        for key in ("ts",) + (("dur",) if ph == "X" else ()):
            v = ev.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                problems.append(f"event {i}: missing/invalid {key!r}")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                problems.append(f"event {i}: missing/invalid {key!r}")
    return problems


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-default tracer (run loops; services own their own)."""
    return _TRACER


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-default tracer; returns the previous one."""
    global _TRACER
    prev, _TRACER = _TRACER, tracer
    return prev
