"""The versioned telemetry event schema and its pluggable sinks.

Before this package, the repo had two incompatible ad-hoc event lists
(``resilience/driver.py``'s report events and ``serving/service.py``'s
service log) — same idea, different shapes, neither versioned. Every
event now flows through one :class:`EventLog`, which stamps each record
with the schema version, the run id, a monotonic per-run sequence
number, and (optionally) the span id of the enclosing telemetry span —
the keys a fleet log scraper needs to merge, order, and correlate
events from thousands of concurrent runs:

``{"event": kind, "time": <unix s>, "run": <id>, "seq": <n>,
"schema": 1, ["span": <id>,] **attrs}``

Sinks are deliberately dumb (``emit(record)`` / ``close()``):

* :class:`ListSink`   — append to a caller-owned list (the report
  dataclasses keep their serializable ``events`` fields);
* :class:`RingSink`   — bounded in-memory deque: a service that logs
  forever holds flat memory (the unbounded ``CampaignService.events``
  fix), with a dropped-record counter so truncation is never silent;
* :class:`JsonlSink`  — one JSON object per line, append-only (the CI
  artifact format);
* :class:`StreamJsonSink` — JSON lines to a stream (stderr by default;
  the ``STENCIL_LOG_FORMAT=json`` backend in ``utils/logging.py``).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

#: bump when a record key changes meaning; scrapers key on this
EVENT_SCHEMA_VERSION = 1


def new_run_id() -> str:
    """A fresh globally-unique run id (12 hex chars)."""
    return uuid.uuid4().hex[:12]


class ListSink:
    """Append records to a caller-owned list (kept serializable)."""

    def __init__(self, records: List[Dict]) -> None:
        self._records = records

    def emit(self, record: Dict) -> None:
        self._records.append(record)

    def close(self) -> None:
        pass


class RingSink:
    """Bounded in-memory ring: the newest ``capacity`` records.

    The fix for append-forever event lists — a service handling
    millions of requests holds flat memory. ``dropped`` counts records
    the ring aged out, so truncation shows up in the payload instead of
    silently shortening history."""

    def __init__(self, capacity: int = 4096) -> None:
        if int(capacity) < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self.dropped = 0
        # readers (records()) run on other threads than the emitting
        # EventLog — snapshotting a deque mid-append raises RuntimeError
        self._lock = threading.Lock()

    def emit(self, record: Dict) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(record)

    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        pass


class JsonlSink:
    """One JSON object per line, appended to ``path`` (flushed per
    record — a crashed run keeps everything it logged)."""

    def __init__(self, path: str) -> None:
        self.path = str(path)
        self._fh = open(self.path, "a", encoding="utf-8")

    def emit(self, record: Dict) -> None:
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()


class StreamJsonSink:
    """JSON lines to a stream; ``stream=None`` resolves ``sys.stderr``
    at emit time (so test harnesses that swap stderr still capture)."""

    def __init__(self, stream=None) -> None:
        self._stream = stream

    def emit(self, record: Dict) -> None:
        import sys

        stream = self._stream if self._stream is not None else sys.stderr
        print(json.dumps(record), file=stream)

    def close(self) -> None:
        pass


class EventLog:
    """The thread-safe stamping front end: every subsystem's events go
    through :meth:`emit`, which versions the record and fans it out to
    every sink."""

    def __init__(self, run_id: Optional[str] = None,
                 sinks: Sequence = (),
                 clock: Callable[[], float] = time.time) -> None:
        self.run_id = run_id or new_run_id()
        self._sinks = list(sinks)
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0

    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    #: record keys the log stamps itself — attrs may not shadow them
    RESERVED = frozenset(("event", "time", "run", "seq", "schema",
                          "span"))

    def emit(self, kind: str, span: Optional[str] = None,
             **attrs) -> Dict:
        """Stamp and fan out one event record; returns the record.
        ``attrs`` may not use the stamped schema keys (:attr:`RESERVED`)
        — a colliding attribute would silently corrupt the run/seq/time
        identity every scraper merges on, so it raises instead.

        Sink fan-out runs UNDER the log lock (stdlib-``logging``
        semantics, deliberately): ``validate_events`` requires per-run
        ``seq`` strictly increasing in sink order, and ``JsonlSink``'s
        per-record flush is the crash-durability contract — emitting
        outside the lock could interleave records out of seq order.
        High-rate paths (the service event log) use the in-memory
        :class:`RingSink`, which does no I/O."""
        bad = self.RESERVED.intersection(attrs)
        if bad:
            raise ValueError(
                f"event attrs may not shadow schema keys: {sorted(bad)}")
        with self._lock:
            seq = self._seq
            self._seq += 1
            record: Dict = {"event": kind, "time": self._clock(),
                            "run": self.run_id, "seq": seq,
                            "schema": EVENT_SCHEMA_VERSION}
            if span is not None:
                record["span"] = span
            record.update(attrs)
            for sink in self._sinks:
                # a failing sink (disk full, closed stream) must not
                # take down the loop being observed, nor starve the
                # remaining sinks of the record — warn on stderr
                # directly (LOG_* may itself route through an EventLog)
                try:
                    sink.emit(record)
                except Exception as e:  # noqa: BLE001
                    import sys

                    print(f"telemetry: {type(sink).__name__}.emit "
                          f"failed: {type(e).__name__}: {e}",
                          file=sys.stderr)
        return record

    def close(self) -> None:
        with self._lock:
            for sink in self._sinks:
                sink.close()


def validate_events(records: Sequence[Dict]) -> List[str]:
    """Schema-check a batch of event records (the CLI/CI validator):
    required keys present, types sane, and per-run sequence numbers
    strictly increasing. Returns human-readable problems (empty =
    valid)."""
    problems: List[str] = []
    last_seq: Dict[str, float] = {}
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            problems.append(f"record {i}: not an object")
            continue
        for key, typ in (("event", str), ("run", str)):
            if not isinstance(rec.get(key), typ):
                problems.append(f"record {i}: missing/invalid {key!r}")
        for key in ("time", "seq", "schema"):
            if not isinstance(rec.get(key), (int, float)) \
                    or isinstance(rec.get(key), bool):
                problems.append(f"record {i}: missing/invalid {key!r}")
        if rec.get("schema") not in (None, EVENT_SCHEMA_VERSION):
            problems.append(
                f"record {i}: schema {rec.get('schema')!r} != "
                f"{EVENT_SCHEMA_VERSION}")
        run, seq = rec.get("run"), rec.get("seq")
        # ordering applies to any numeric seq (an external serializer
        # may write 1.0 — the type gate above accepts it, so the
        # monotonicity gate must too)
        if isinstance(run, str) and isinstance(seq, (int, float)) \
                and not isinstance(seq, bool):
            if run in last_seq and seq <= last_seq[run]:
                problems.append(
                    f"record {i}: seq {seq} not increasing for run "
                    f"{run} (last {last_seq[run]})")
            last_seq[run] = seq
    return problems
