"""CLI entry: ``python -m stencil_tpu.telemetry``.

Subcommands (all artifact-facing — none touch accelerators):

* ``snapshot PATH``        — render a metrics snapshot JSON (the
  ``--metrics-json`` artifact) as Prometheus-style text; ``--json``
  re-dumps it (schema-checked) instead.
* ``validate-trace PATH``  — structural validation of a Chrome
  trace-event JSON export (the ``--trace-json`` artifact) against the
  format Perfetto loads; nonzero exit on problems (the CI gate).
* ``validate-events PATH`` — schema-check a unified event log: a JSON
  payload with an ``events`` array (service / resilience artifacts),
  a bare array, or JSONL; nonzero exit on problems.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _load_events(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        payload = json.loads(text)
    except ValueError:
        # JSONL: one record per line
        return [json.loads(line) for line in text.splitlines() if line]
    if isinstance(payload, dict):
        events = payload.get("events")
        if isinstance(events, list):
            return events
        if "event" in payload:
            # a one-line JSONL file parses as a single record dict
            return [payload]
        raise ValueError(f"{path}: no 'events' array in payload")
    if isinstance(payload, list):
        return payload
    raise ValueError(f"{path}: neither an event array nor a payload")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stencil_tpu.telemetry",
        description="telemetry artifact tools: render metrics "
                    "snapshots, validate Perfetto traces and unified "
                    "event logs")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_snap = sub.add_parser("snapshot",
                            help="render a metrics snapshot JSON")
    p_snap.add_argument("path")
    p_snap.add_argument("--json", action="store_true",
                        help="re-dump the (schema-checked) snapshot "
                             "instead of rendering text")

    p_tr = sub.add_parser("validate-trace",
                          help="validate a Chrome trace-event export")
    p_tr.add_argument("path")

    p_ev = sub.add_parser("validate-events",
                          help="schema-check a unified event log")
    p_ev.add_argument("path")

    args = parser.parse_args(argv)

    if args.cmd == "snapshot":
        from .metrics import METRICS_SCHEMA_VERSION, render_snapshot_text

        try:
            with open(args.path, encoding="utf-8") as f:
                snap = json.load(f)
        except (OSError, ValueError) as e:
            print(f"telemetry: cannot load snapshot: {e}",
                  file=sys.stderr)
            return 2
        if snap.get("schema") != METRICS_SCHEMA_VERSION:
            print(f"telemetry: snapshot schema {snap.get('schema')!r} "
                  f"!= {METRICS_SCHEMA_VERSION}", file=sys.stderr)
            return 1
        if args.json:
            json.dump(snap, sys.stdout, indent=1)
            print()
        else:
            sys.stdout.write(render_snapshot_text(snap))
        return 0

    if args.cmd == "validate-trace":
        from .spans import validate_chrome_trace

        problems = validate_chrome_trace(args.path)
        for p in problems:
            print(f"  BAD  {p}")
        if problems:
            print(f"telemetry: trace {args.path}: "
                  f"{len(problems)} problem(s)")
            return 1
        with open(args.path, encoding="utf-8") as f:
            n = len(json.load(f).get("traceEvents", []))
        print(f"telemetry: trace {args.path} OK ({n} events)")
        return 0

    # validate-events
    from .events import validate_events

    try:
        events = _load_events(args.path)
    except (OSError, ValueError) as e:
        print(f"telemetry: cannot load events: {e}", file=sys.stderr)
        return 2
    problems = validate_events(events)
    for p in problems:
        print(f"  BAD  {p}")
    if problems:
        print(f"telemetry: events {args.path}: "
              f"{len(problems)} problem(s)")
        return 1
    runs = {e.get("run") for e in events}
    print(f"telemetry: events {args.path} OK ({len(events)} records, "
          f"{len(runs)} run(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
