"""The stdlib ``/metrics`` endpoint (no server framework, no deps).

A :class:`MetricsServer` exposes one :class:`..telemetry.metrics.
MetricsRegistry` over HTTP the way a Prometheus scraper expects:

* ``GET /metrics``       — text exposition (format 0.0.4);
* ``GET /metrics.json``  — the JSON snapshot (the CI artifact shape);
* ``GET /healthz``       — liveness (200 "ok").

Built on ``http.server.ThreadingHTTPServer`` in a daemon thread; bind
``port=0`` for an ephemeral port (tests; the bound port is in
``.port`` after :meth:`start`). ``apps/serve.py --metrics-port`` is
the production-shaped front end.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import MetricsRegistry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Serve one registry's metrics until :meth:`stop`."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1") -> None:
        self.registry = registry
        self.host = host
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind and serve in a daemon thread; returns the bound port."""
        if self._httpd is not None:
            raise RuntimeError("metrics server already started")
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes,
                      ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 - stdlib contract
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._send(200,
                               registry.to_prometheus_text().encode(),
                               PROMETHEUS_CONTENT_TYPE)
                elif path == "/metrics.json":
                    body = json.dumps(registry.snapshot()).encode()
                    self._send(200, body, "application/json")
                elif path == "/healthz":
                    self._send(200, b"ok\n", "text/plain")
                else:
                    self._send(404, b"not found\n", "text/plain")

            def log_message(self, *args) -> None:
                pass  # scrapes are not stderr news

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="stencil-metrics-http",
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
