"""Shared CLI plumbing for the reference-parity app suite
(reference: bin/ — argparse flags, CSV result lines, Statistics)."""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stencil_tpu.numerics import Statistics  # noqa: E402
from stencil_tpu.parallel.methods import Method  # noqa: E402


def add_device_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--fake-cpu", type=int, default=0, metavar="N",
                   help="run on N virtual CPU devices (the analog of the "
                        "reference's GPU oversubscription, "
                        "test/test_exchange.cu:52)")


def apply_device_flags(args) -> None:
    """Must run before any jax device use (backend init is lazy)."""
    from stencil_tpu.utils.config import apply_fake_cpu, enable_compile_cache
    apply_fake_cpu(getattr(args, "fake_cpu", 0))
    enable_compile_cache()


def add_dtype_flags(p: argparse.ArgumentParser) -> None:
    """--f64 / --bf16 (the reference's float/double templating analog;
    bf16 is the TPU-native half-traffic option)."""
    g = p.add_mutually_exclusive_group()
    g.add_argument("--f64", action="store_true")
    g.add_argument("--bf16", action="store_true",
                   help="bfloat16 fields: half the HBM traffic on the "
                        "bandwidth-bound fused kernels")


def dtype_from_args(args):
    """Resolve the field dtype; must run after apply_device_flags
    (x64 needs the config update before first use)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if getattr(args, "f64", False):
        jax.config.update("jax_enable_x64", True)
        return np.float64
    return jnp.bfloat16 if getattr(args, "bf16", False) else np.float32


KERNEL_CHOICES = ("auto", "wrap", "halo", "xla", "pallas")


def add_method_flags(p: argparse.ArgumentParser) -> None:
    """The analog of the reference's per-method CLI flags
    (reference: bin/jacobi3d.cu:107-122 --staged/--colo/--peer/--kernel)."""
    p.add_argument("--slab", action="store_true",
                   help="per-axis slab ppermute (default)")
    p.add_argument("--packed", action="store_true",
                   help="pack all quantities per direction into one buffer")
    p.add_argument("--allgather", action="store_true",
                   help="all-gather control strategy")
    p.add_argument("--pallas-dma", action="store_true",
                   help="explicit inter-chip RDMA (Pallas) exchange")


def methods_from_args(args) -> Method:
    m = Method.NONE
    if getattr(args, "slab", False):
        m |= Method.PpermuteSlab
    if getattr(args, "packed", False):
        m |= Method.PpermutePacked
    if getattr(args, "allgather", False):
        m |= Method.AllGather
    if getattr(args, "pallas_dma", False):
        m |= Method.PallasDMA
    return m if m != Method.NONE else Method.Default


def add_dcn_flags(p: argparse.ArgumentParser) -> None:
    """Hierarchical slice/host tier (the reference's node-aware
    NodePartition level, partition.hpp:120-256)."""
    p.add_argument("--dcn-axis", default=None,
                   choices=("x", "y", "z", "auto"),
                   help="block this grid axis across slices/hosts so "
                        "only its halo sweep crosses the DCN ('auto' "
                        "derives it from the interface-minimizing "
                        "split); omit for a flat single-tier mesh")
    p.add_argument("--fake-slices", type=int, default=0, metavar="S",
                   help="pretend the devices form S equal slices "
                        "(testing the DCN tier without multihost "
                        "hardware)")


def dcn_from_args(args):
    """(dcn_axis, dcn_groups) kwargs for the models."""
    axis = getattr(args, "dcn_axis", None)
    fake = getattr(args, "fake_slices", 0)
    if axis is None and not fake:
        return {}
    groups = None
    if fake:
        import jax
        devs = list(jax.devices())
        if len(devs) % fake:
            raise SystemExit(f"{len(devs)} devices not divisible into "
                             f"{fake} fake slices")
        per = len(devs) // fake
        groups = [devs[i * per:(i + 1) * per] for i in range(fake)]
    return {"dcn_axis": axis or "auto", "dcn_groups": groups}


def dcn_mesh_shape(args, xfree: bool):
    """The weak-scaling mesh shape when the DCN tier is requested:
    the slice-blocked axis must be divisible by the slice count, which
    the flat default_mesh_shape* helpers don't know about. Returns None
    when no DCN tier is requested (callers fall back to the flat
    helpers)."""
    kw = dcn_from_args(args)
    if not kw:
        return None
    import jax
    from stencil_tpu.parallel.mesh import default_mesh_shape_dcn
    from stencil_tpu.parallel.multihost import slice_groups
    groups = kw["dcn_groups"] or slice_groups()
    axis = {"x": 0, "y": 1, "z": 2}.get(kw["dcn_axis"], 2)
    if xfree and axis == 0 and len(groups) > 1:
        raise SystemExit("--dcn-axis x shards the lane axis, which the "
                         "halo kernel path cannot use; pick y/z or "
                         "--kernel xla")
    return default_mesh_shape_dcn(len(jax.devices()), len(groups),
                                  axis=axis, xfree=xfree)


def add_placement_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--trivial", action="store_true",
                   help="trivial placement instead of node-aware")
    p.add_argument("--random", action="store_true",
                   help="random placement (experimental control)")


def placement_from_args(args):
    from stencil_tpu.placement import PlacementStrategy
    if getattr(args, "random", False):
        return PlacementStrategy.IntraNodeRandom
    if getattr(args, "trivial", False):
        return PlacementStrategy.Trivial
    return PlacementStrategy.NodeAware


def csv_line(*fields) -> str:
    return ",".join(str(f) for f in fields)


def timed_samples(fn, sync, iters: int, warmup: int = 2) -> Statistics:
    """Time ``fn()`` ``iters`` times (after warmup), fencing with
    ``sync()``; returns the Statistics accumulator."""
    for _ in range(warmup):
        fn()
    sync()
    stats = Statistics()
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        sync()
        stats.insert(time.perf_counter() - t0)
    return stats


# ---------------------------------------------------------------------------
# the ONE steps/s measurement contract (performance observatory)
#
# Every app's steps/s claim used to re-implement its own warmup/measure/
# block loop (bench_exchange's jacobi_steps_per_s, jacobi3d's and pic's
# timed_samples closures) — three chances for the contract to drift.
# These two helpers are the single source: compile+warm OUTSIDE the
# timed window, fence with block() on both sides, count only steps that
# actually advanced.


def grouped_steps_per_s(run, block, iters: int, group: int = 1):
    """Whole-loop steps/s: ``run(n)`` advances n steps in the engine's
    fused loop; ``iters`` is rounded to whole ``group``-sized blocks so
    differently-blocked configurations compare the same work (temporal
    depth s, megastep check_every). Returns ``(steps, seconds,
    steps_per_s)``."""
    g = max(int(group), 1)
    n = max(int(iters), g)
    n -= n % g
    run(g)       # compile + warm outside the timed window
    block()
    t0 = time.perf_counter()
    run(n)
    block()
    dt = time.perf_counter() - t0
    return n, dt, n / dt


def sampled_steps_per_s(one, block, samples: int, batch: int,
                        warmup: int = 2):
    """Sampled steps/s for the CSV-reporting apps: ``one()`` advances
    ``batch`` steps, timed ``samples`` times after ``warmup`` calls
    (min/trimean come from the returned Statistics). Returns
    ``(stats, steps_per_s)`` with steps/s from the trimean — the same
    robust figure the CSV line prints."""
    stats = timed_samples(one, block, max(int(samples), 1), warmup)
    return stats, batch / stats.trimean()


def megastep_race(make_engine, make_sentinel, fields_fn, k: int,
                  n: int, probe_every: int = 1):
    """The ONE fused-vs-stepwise megastep race protocol (shared by
    bench_exchange's three legs and pic.py's smoke race): the stepwise
    side pays one step + one health-probe dispatch per iteration, the
    fused side ONE megastep per ``k`` steps with the probe trace
    in-graph — same problem, same health coverage, only the
    host/device boundary moves. Engines expose ``step()`` /
    ``make_segment(k, probe_every)`` / ``block()``; compile + warm
    happen outside both timed windows. Returns
    ``(stepwise_steps_per_s, fused_steps_per_s, fused_over_stepwise)``."""
    eng = make_engine()
    sent = make_sentinel(eng)
    eng.step()     # compile + warm outside the timed window
    sent.probe(fields_fn(eng), 0)
    sent.poll(block=True)
    eng.block()
    t0 = time.perf_counter()
    for i in range(n):
        eng.step()
        sent.probe(fields_fn(eng), i + 1)
        sent.poll()
    sent.poll(block=True)
    eng.block()
    step_dt = time.perf_counter() - t0

    engf = make_engine()
    fsent = make_sentinel(engf)
    seg = engf.make_segment(k, probe_every=probe_every)
    tr = seg.run(0)    # compile + warm
    fsent.observe_segment(tr.array, tr.abs_steps)
    fsent.poll(block=True)
    fsent.reset()
    engf.block()
    t0 = time.perf_counter()
    done = 0
    while done < n:
        tr = seg.run(done)
        done += k
        fsent.observe_segment(tr.array, tr.abs_steps)
        fsent.poll()
    fsent.poll(block=True)
    engf.block()
    fused_dt = time.perf_counter() - t0
    return n / step_dt, n / fused_dt, step_dt / fused_dt


def add_bench_record_flags(p: argparse.ArgumentParser) -> None:
    """``--ledger``: where ``--json-out`` runs ALSO append their
    versioned observatory bench record (the append-only perf
    trajectory, ``stencil_tpu/observatory/ledger.py``)."""
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="bench trajectory ledger (JSONL) the --json-out"
                        " record is also appended to; default "
                        "$STENCIL_BENCH_LEDGER, else bench/ledger.jsonl"
                        " in this checkout; pass '' (or export "
                        "STENCIL_BENCH_LEDGER='') to disable")


def resolve_ledger_path(args):
    """The ledger the record lands in, or None when disabled. An env
    var SET to the empty string disables just like ``--ledger ''`` —
    only a genuinely unset variable falls through to the committed
    checkout ledger."""
    led = getattr(args, "ledger", None)
    if led is None:
        led = os.environ.get("STENCIL_BENCH_LEDGER")
        if led is None:
            led = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "bench", "ledger.jsonl")
    return led or None


def emit_bench_artifacts(args, payload, source: str):
    """The one place a bench's measured numbers leave the process:
    write the legacy ``--json-out`` artifact AND append the versioned
    observatory ledger record(s) derived from the SAME payload (one
    converter serves live emission and legacy backfill —
    ``observatory.ledger.payload_records`` — so a run and its
    backfilled ancestors share a trajectory group by construction).
    No-op without ``--json-out``. Returns the ledger path (None when
    disabled)."""
    import json

    if not getattr(args, "json_out", ""):
        return None
    with open(args.json_out, "w") as f:
        json.dump(payload, f, indent=2)
    ledger = resolve_ledger_path(args)
    if ledger:
        from stencil_tpu.observatory.ledger import (append_record,
                                                    payload_records)
        records, skipped = payload_records(payload, source,
                                           provenance="measured",
                                           created=time.time())
        for rec in records:
            # tiling-plan provenance: stamp the VMEM planner's
            # prescribed block shapes onto the record AFTER the
            # fingerprint is fixed — a provenance note (future real-TPU
            # numbers group against the shapes that produced them),
            # never a trajectory-group fork
            if payload.get("tiling_plan"):
                rec["config"].setdefault("tiling_plan",
                                         payload["tiling_plan"])
            # link-class provenance: the per-(axis, link_class) byte
            # SHARES of the modeled traffic matrix, stamped AFTER the
            # fingerprint is fixed — records group the same with or
            # without it (trajectories never fork), future records
            # just carry which fabric tier their bytes rode
            if payload.get("link_classes"):
                rec["config"].setdefault(
                    "link_classes",
                    {k: round(v["share"], 6)
                     for k, v in payload["link_classes"].items()})
            # wire-layout provenance: which halo message geometry the
            # measured bytes rode (slab / irredundant packed boxes),
            # stamped AFTER the fingerprint is fixed — same rule as
            # above, a note that never forks a trajectory group
            if payload.get("wire_layout"):
                rec["config"].setdefault("wire_layout",
                                         payload["wire_layout"])
            append_record(ledger, rec)
        for s in skipped:
            print(f"{source}: ledger skip: {s}", file=sys.stderr)
        print(f"{source}: appended {len(records)} ledger record(s) -> "
              f"{ledger}", file=sys.stderr)
    return ledger
