"""Shared CLI plumbing for the reference-parity app suite
(reference: bin/ — argparse flags, CSV result lines, Statistics)."""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stencil_tpu.numerics import Statistics  # noqa: E402
from stencil_tpu.parallel.methods import Method  # noqa: E402


def add_device_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--fake-cpu", type=int, default=0, metavar="N",
                   help="run on N virtual CPU devices (the analog of the "
                        "reference's GPU oversubscription, "
                        "test/test_exchange.cu:52)")


def apply_device_flags(args) -> None:
    """Must run before any jax device use (backend init is lazy)."""
    n = getattr(args, "fake_cpu", 0)
    if n:
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", n)


def add_method_flags(p: argparse.ArgumentParser) -> None:
    """The analog of the reference's per-method CLI flags
    (reference: bin/jacobi3d.cu:107-122 --staged/--colo/--peer/--kernel)."""
    p.add_argument("--slab", action="store_true",
                   help="per-axis slab ppermute (default)")
    p.add_argument("--packed", action="store_true",
                   help="pack all quantities per direction into one buffer")
    p.add_argument("--allgather", action="store_true",
                   help="all-gather control strategy")
    p.add_argument("--pallas-dma", action="store_true",
                   help="explicit inter-chip RDMA (Pallas) exchange")


def methods_from_args(args) -> Method:
    m = Method.NONE
    if getattr(args, "slab", False):
        m |= Method.PpermuteSlab
    if getattr(args, "packed", False):
        m |= Method.PpermutePacked
    if getattr(args, "allgather", False):
        m |= Method.AllGather
    if getattr(args, "pallas_dma", False):
        m |= Method.PallasDMA
    return m if m != Method.NONE else Method.Default


def add_placement_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--trivial", action="store_true",
                   help="trivial placement instead of node-aware")
    p.add_argument("--random", action="store_true",
                   help="random placement (experimental control)")


def placement_from_args(args):
    from stencil_tpu.placement import PlacementStrategy
    if getattr(args, "random", False):
        return PlacementStrategy.IntraNodeRandom
    if getattr(args, "trivial", False):
        return PlacementStrategy.Trivial
    return PlacementStrategy.NodeAware


def csv_line(*fields) -> str:
    return ",".join(str(f) for f in fields)


def timed_samples(fn, sync, iters: int, warmup: int = 2) -> Statistics:
    """Time ``fn()`` ``iters`` times (after warmup), fencing with
    ``sync()``; returns the Statistics accumulator."""
    for _ in range(warmup):
        fn()
    sync()
    stats = Statistics()
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        sync()
        stats.insert(time.perf_counter() - t0)
    return stats
