"""Ensemble campaign service front end (stencil_tpu/serving).

Drives the async multi-tenant campaign service: a first wave of
concurrent fake-tenant campaigns (distinct tenants, one shared problem
fingerprint) is submitted and served as ONE batched ensemble dispatch
stream, then a second fingerprint-identical wave proves the warm path:
zero recompiles (engine cache) and zero tuner measurements (plan
cache). The event log JSON is the CI service-smoke artifact.

Examples:
  python serve.py --tenants 3 --steps 6 --fake-cpu 8 \\
      --events-json events.json --fake-timer --tune-cache plans.json
  python serve.py --tenants 2 --model astaroth --steps 2 --fake-cpu 8
"""

import argparse
import shutil
import sys
import tempfile

from _common import add_device_flags, apply_device_flags


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_device_flags(p)
    p.add_argument("--model", choices=("jacobi", "astaroth"),
                   default="jacobi")
    p.add_argument("--x", type=int, default=8)
    p.add_argument("--y", type=int, default=8)
    p.add_argument("--z", type=int, default=8)
    p.add_argument("--tenants", type=int, default=3,
                   help="concurrent fake tenants in the first wave")
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--width", type=int, default=8,
                   help="ensemble width (members per dispatch)")
    p.add_argument("--ckpt-every", type=int, default=2)
    p.add_argument("--check-every", type=int, default=1)
    p.add_argument("--snapshot-every", type=int, default=3)
    p.add_argument("--second-wave", type=int, default=1,
                   help="fingerprint-identical requests submitted "
                        "after the first wave (the warm path)")
    p.add_argument("--chaos-nan", type=int, default=0, metavar="STEP",
                   help="poison tenant 0's campaign at this member "
                        "step (proves member-isolated rollback)")
    p.add_argument("--max-retries", type=int, default=None,
                   metavar="N",
                   help="per-campaign rollback budget before it fails "
                        "(default: the service default; 0 + "
                        "--chaos-nan drives the failure path, which "
                        "still exports every telemetry artifact)")
    p.add_argument("--root", default="",
                   help="checkpoint namespace root (default: tmpdir)")
    p.add_argument("--keep-root", action="store_true")
    p.add_argument("--events-json", default="",
                   help="write the service event log + stats here")
    p.add_argument("--metrics-port", type=int, default=-1,
                   metavar="PORT",
                   help="serve Prometheus /metrics (and /metrics.json)"
                        " on this port while running (0 = ephemeral; "
                        "default: disabled)")
    p.add_argument("--metrics-host", default="127.0.0.1",
                   metavar="HOST",
                   help="bind address for --metrics-port (default "
                        "loopback; 0.0.0.0 for a remote scraper)")
    p.add_argument("--metrics-json", default="", metavar="PATH",
                   help="write the final metrics snapshot JSON here "
                        "(the CI telemetry artifact)")
    p.add_argument("--trace-json", default="", metavar="PATH",
                   help="write the Chrome trace-event JSON of the "
                        "service spans here (load in Perfetto)")
    p.add_argument("--fuse-segments",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="megastep serving (default on): each batch "
                        "segment is ONE fused dispatch carrying the "
                        "per-member probe trace in-graph; "
                        "--no-fuse-segments restores the step-loop + "
                        "separate-probe path")
    p.add_argument("--fake-timer", action="store_true",
                   help="tune exchange plans with the deterministic "
                        "FakeTimer (CI: no hardware dependence)")
    p.add_argument("--tune-cache", default="",
                   help="tuning-plan cache path (shared across runs "
                        "-> the second process is a plan-cache hit)")
    p.add_argument("--flight-dir", default="", metavar="DIR",
                   help="arm the flight recorder: bounded black-box "
                        "dumps (events + spans + metrics + probe "
                        "history) land here on sentinel trip, "
                        "preemption, and unhandled batch errors "
                        "(default $STENCIL_FLIGHT_RECORDER_DIR)")
    p.add_argument("--retune-on-drift", action="store_true",
                   help="perf-drift healing: K consecutive attributed "
                        "segments outside tolerance invalidate the "
                        "plan-cache record so the next tune "
                        "re-measures")
    args = p.parse_args()
    apply_device_flags(args)

    from stencil_tpu.serving import CampaignRequest, CampaignService
    from stencil_tpu.tuning import FakeTimer

    root = args.root or tempfile.mkdtemp(prefix="serve_root.")
    svc = CampaignService(
        root, width=args.width,
        tuner_timer=FakeTimer() if args.fake_timer else None,
        plan_cache_path=args.tune_cache or None,
        fuse_segments=args.fuse_segments,
        flight_recorder_dir=args.flight_dir or None,
        retune_on_drift=args.retune_on_drift)

    metrics_server = None
    if args.metrics_port >= 0:
        from stencil_tpu.telemetry import MetricsServer

        metrics_server = MetricsServer(svc.metrics,
                                       port=args.metrics_port,
                                       host=args.metrics_host)
        port = metrics_server.start()
        print(f"metrics: http://{args.metrics_host}:{port}/metrics",
              file=sys.stderr)

    def request(tenant: str, campaign: str, seed: int,
                chaos=None) -> CampaignRequest:
        params = ({"hot_temp": 1.0 + 0.05 * seed}
                  if args.model == "jacobi" else
                  {"nu_visc": 5e-3 * (1.0 + 0.1 * seed)})
        kw = {} if args.max_retries is None \
            else {"max_retries": args.max_retries}
        return CampaignRequest(
            tenant=tenant, campaign=campaign, model=args.model,
            grid=(args.x, args.y, args.z), n_steps=args.steps,
            ckpt_every=args.ckpt_every, check_every=args.check_every,
            snapshot_every=args.snapshot_every, init_seed=100 + seed,
            params=params, chaos_nan_step=chaos, **kw)

    # artifacts export on the FAILURE path too — a failed campaign is
    # exactly when the metrics/trace/event log are needed
    try:
        # submit the whole first wave BEFORE the worker starts so
        # admission packs it into one fingerprint-compatible batch
        handles = [svc.submit(request(
            f"tenant{i}", "wave1", i,
            chaos=args.chaos_nan if (args.chaos_nan and i == 0)
            else None))
            for i in range(args.tenants)]
        svc.start()
        for h in handles:
            r = h.result(timeout=600)
            print(f"{r.tenant}/{r.campaign}: steps={r.steps} "
                  f"rollbacks={r.rollbacks} "
                  f"snapshots={[s for s, _ in r.snapshots]}")

        for j in range(args.second_wave):
            h = svc.submit(request(f"tenant{args.tenants + j}",
                                   "wave2", args.tenants + j))
            r = h.result(timeout=600)
            print(f"{r.tenant}/{r.campaign}: steps={r.steps} "
                  f"rollbacks={r.rollbacks} (warm path)")

        s = svc.stats
        print(f"stats: batches={s.batches} compiles={s.compiles} "
              f"plan_cache_hits={s.plan_cache_hits} "
              f"tuner_measurements={s.tuner_measurements} "
              f"completed={s.completed} failed={s.failed} "
              f"rollbacks={s.rollbacks}")
    finally:
        # each step is best-effort: one unwritable artifact must not
        # mask the CampaignFailed being raised nor skip the others
        def attempt(what, fn) -> None:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - report, don't mask
                print(f"warning: {what} failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)

        attempt("service stop", svc.stop)
        if args.events_json:
            attempt("event log export", lambda: (
                svc.write_events(args.events_json),
                print(f"event log -> {args.events_json}",
                      file=sys.stderr)))
        if args.metrics_json:
            attempt("metrics snapshot export", lambda: (
                svc.metrics.write_snapshot(args.metrics_json),
                print(f"metrics snapshot -> {args.metrics_json}",
                      file=sys.stderr)))
        if args.trace_json:
            attempt("span trace export", lambda: (
                svc.export_trace(args.trace_json),
                print(f"span trace -> {args.trace_json}",
                      file=sys.stderr)))
        if metrics_server is not None:
            attempt("metrics server stop", metrics_server.stop)
        if not args.root and not args.keep_root:
            shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
