#!/usr/bin/env python
"""Neighbor point-to-point bandwidth sweep over the device fabric.

Reference parity: bin/pingpong.cu:19-28 — message sizes 2^min..2^max
bytes bounced between a device pair; here a ppermute ring shift between
mesh neighbors (the ICI point-to-point path).
"""

import argparse
import time

from _common import add_device_flags, apply_device_flags, csv_line


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--min", type=int, default=10, help="log2 min bytes")
    ap.add_argument("--max", type=int, default=24, help="log2 max bytes")
    ap.add_argument("--iters", "-n", type=int, default=20)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from stencil_tpu.numerics import Statistics
    from stencil_tpu.utils.timers import device_sync

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        print("pingpong: need >= 2 devices; have", n)
        return
    mesh = jax.make_mesh((n,), ("x",))
    spec = P("x")

    def shift(x):
        return lax.ppermute(x, "x", [(i, (i + 1) % n) for i in range(n)])

    sm = jax.jit(jax.shard_map(shift, mesh=mesh, in_specs=spec,
                               out_specs=spec, check_vma=False))

    print(csv_line("pingpong", "bytes_per_dev", "trimean_s", "GBps_per_dev"))
    for p in range(args.min, args.max + 1):
        nbytes = 1 << p
        elems = max(nbytes // 4, 1) * n
        x = jnp.zeros((elems,), jnp.float32)
        x = jax.device_put(x, NamedSharding(mesh, spec))
        y = sm(x)
        device_sync(y)
        stats = Statistics()
        for _ in range(args.iters):
            t0 = time.perf_counter()
            y = sm(y)
            device_sync(y)
            stats.insert(time.perf_counter() - t0)
        tm = stats.trimean()
        print(csv_line("pingpong", nbytes, f"{tm:.6e}",
                       f"{nbytes / tm / 1e9:.3f}"))


if __name__ == "__main__":
    main()
