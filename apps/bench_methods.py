#!/usr/bin/env python
"""Exchange-strategy comparison sweep.

Reference parity: bin/bench_alltoallv.cu (compares exchange patterns
over a comm matrix) + bin/bench_mpi_pack.cu (pack-kernel+contiguous
send vs MPI derived datatypes). The TPU analog sweeps every exchange
Method on one configuration — per-quantity slab ppermute vs packed
single-buffer ppermute vs all-gather vs explicit Pallas RDMA — and
reports trimean seconds and B/s for each, one CSV line per method.
"""

import argparse

from _common import (add_device_flags, apply_device_flags, csv_line,
                     timed_samples)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=64, help="per-device x size")
    ap.add_argument("--y", type=int, default=64)
    ap.add_argument("--z", type=int, default=64)
    ap.add_argument("--radius", "-r", type=int, default=2)
    ap.add_argument("--fields", type=int, default=4)
    ap.add_argument("--iters", "-n", type=int, default=20)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax
    import numpy as np

    from stencil_tpu.distributed import DistributedDomain
    from stencil_tpu.parallel.mesh import default_mesh_shape
    from stencil_tpu.parallel.methods import Method
    from stencil_tpu.utils.timers import device_sync

    ndev = len(jax.devices())
    mesh_shape = default_mesh_shape(ndev)
    for method in (Method.PpermuteSlab, Method.PpermutePacked,
                   Method.AllGather, Method.PallasDMA):
        dd = DistributedDomain(args.x * mesh_shape.x, args.y * mesh_shape.y,
                               args.z * mesh_shape.z)
        dd.set_mesh_shape(mesh_shape)
        dd.set_radius(args.radius)
        dd.set_methods(method)
        for i in range(args.fields):
            dd.add_data(f"q{i}", np.float32)
        dd.realize()
        stats = timed_samples(dd.exchange, lambda: device_sync(dd.curr),
                              args.iters)
        total = dd.exchange_bytes_total()
        tm = stats.trimean()
        print(csv_line("bench_methods", method, ndev,
                       args.x, args.y, args.z, args.radius, args.fields,
                       total, f"{tm:.6e}", f"{total / tm:.6e}"))


if __name__ == "__main__":
    main()
