#!/usr/bin/env python
"""Comm/compute overlap study on the Jacobi-3D step.

Reference parity: bin/measure_buf_exchange.cu (overlap study with a
clock-spin kernel riding alongside the exchange). The TPU analog times
four programs at the same size — exchange only, fused step (exchange +
stencil in program order), overlapped step (interior split off the
exchange's data dependencies) — and reports how much of the exchange
the overlapped schedule hides:

    overlap_efficiency = (t_fused - t_overlap) / t_exchange
"""

import argparse

from _common import add_device_flags, apply_device_flags, csv_line, timed_samples


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=64, help="per-device x size")
    ap.add_argument("--y", type=int, default=64)
    ap.add_argument("--z", type=int, default=64)
    ap.add_argument("--iters", "-n", type=int, default=20)
    ap.add_argument("--model", default="jacobi",
                    choices=("jacobi", "mhd"),
                    help="mhd: the astaroth integrator, where the "
                         "reference's overlap machinery earns its keep "
                         "(3 exchanges/iteration; "
                         "astaroth/astaroth.cu:552-646)")
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax
    import numpy as np

    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.parallel.mesh import (default_mesh_shape,
                                           default_mesh_shape_xfree)
    from stencil_tpu.utils.timers import device_sync

    ndev = len(jax.devices())
    # x-unsharded so the overlapped runs can take the in-kernel RDMA
    # paths (ops/pallas_overlap.py, ops/pallas_mhd_overlap.py) rather
    # than the XLA-schedule split
    mesh_shape = (default_mesh_shape_xfree(ndev) if ndev > 1
                  else default_mesh_shape(ndev))
    gx, gy, gz = (args.x * mesh_shape.x, args.y * mesh_shape.y,
                  args.z * mesh_shape.z)
    if args.model == "mhd":
        # the MHD halo/overlap kernel family needs 8-row tiles (the
        # fused megakernels' layout contract) — fail with the actual
        # constraint, not a deep ValueError
        if args.z % 8 or args.y % 8:
            raise SystemExit("--model mhd needs per-device --z/--y "
                             "multiples of 8 (the fused MHD kernels' "
                             "tile contract)")
        measure_mhd(args, mesh_shape, gx, gy, gz, ndev)
        return

    # all three programs use the same kernel family so the efficiency
    # ratio is interpretable: fused = slab exchange THEN halo kernel
    # (serialized); overlap = in-kernel RDMA hidden behind the compute;
    # exchange_only = exactly the slab-exchange program fused runs.
    results = {}
    kern = "halo" if ndev > 1 else "auto"
    fused = Jacobi3D(gx, gy, gz, mesh_shape=mesh_shape, dtype=np.float32,
                     kernel=kern)
    fused.init()
    stats = timed_samples(fused.step, fused.block, args.iters)
    results["fused"] = stats.trimean()

    if ndev > 1:
        from functools import partial

        from jax.sharding import NamedSharding, PartitionSpec as P

        from stencil_tpu.parallel.exchange import exchange_interior_slabs
        from stencil_tpu.parallel.mesh import mesh_dim

        dd = fused.dd
        counts = mesh_dim(dd.mesh)
        esub = 8 if dd.local_size.y % 8 == 0 else 1
        spec = P("z", "y", "x")
        sm = jax.jit(jax.shard_map(
            partial(exchange_interior_slabs, mesh_counts=counts, rz=1,
                    ry=esub),
            mesh=dd.mesh, in_specs=spec, out_specs=spec,
            check_vma=False))
        q = jax.device_put(
            np.zeros((gz, gy, gx), np.float32),
            NamedSharding(dd.mesh, spec))
        out = [None]

        def ex_only():
            out[0] = sm(q)

        stats = timed_samples(ex_only, lambda: device_sync(out[0]),
                              args.iters)
        results["exchange_only"] = stats.trimean()
    else:
        dd = fused.dd
        stats = timed_samples(dd.exchange, lambda: device_sync(dd.curr),
                              args.iters)
        results["exchange_only"] = stats.trimean()

    over = Jacobi3D(gx, gy, gz, mesh_shape=mesh_shape, dtype=np.float32,
                    overlap=True, kernel=kern)
    over.init()
    stats = timed_samples(over.step, over.block, args.iters)
    results["overlap"] = stats.trimean()

    _report("measure_overlap", results, ndev, gx, gy, gz)


def _report(label: str, results: dict, ndev: int, gx: int, gy: int,
            gz: int) -> None:
    """The shared efficiency line: how much of the standalone exchange
    time the overlapped schedule hides."""
    hidden = results["fused"] - results["overlap"]
    eff = (hidden / results["exchange_only"]
           if results["exchange_only"] else 0.0)
    print(csv_line(label, ndev, gx, gy, gz,
                   f"{results['exchange_only']:.6e}",
                   f"{results['fused']:.6e}",
                   f"{results['overlap']:.6e}",
                   f"{eff:.3f}"))


def measure_mhd(args, mesh_shape, gx: int, gy: int, gz: int,
                ndev: int) -> None:
    """Overlap study on the MHD integrator: sequential halo path
    (exchange THEN fused substep, 3x per iteration) vs the in-kernel
    RDMA overlap path, with the standalone slab exchange as the
    denominator — all three programs share the kernel family and the
    byte accounting (exchange_stats), so
    overlap_efficiency = (t_halo - t_overlap) / t_exchange is
    interpretable. Reference: bin/measure_buf_exchange.cu applied to
    the app that runs 3 exchanges per iteration."""
    import numpy as np

    from stencil_tpu.models.astaroth import Astaroth

    # halo family on ANY device count (single chip: wrapped slabs) so
    # all three programs share one kernel family — auto would pick the
    # exchange-free wrap path single-chip and void the ratio
    kern = "halo"
    results = {}
    fused = Astaroth(gx, gy, gz, mesh_shape=mesh_shape,
                     dtype=np.float32, kernel=kern)
    fused.init()
    stats = timed_samples(fused.step, fused.block, args.iters)
    results["fused"] = stats.trimean()
    # per-iteration standalone exchange estimate, same rounds/radii as
    # the fused path performs
    results["exchange_only"] = fused.measure_exchange_seconds()
    del fused

    over = Astaroth(gx, gy, gz, mesh_shape=mesh_shape,
                    dtype=np.float32, kernel=kern, overlap=True)
    over.init()
    stats = timed_samples(over.step, over.block, args.iters)
    results["overlap"] = stats.trimean()
    _report("measure_overlap_mhd", results, ndev, gx, gy, gz)


if __name__ == "__main__":
    main()
