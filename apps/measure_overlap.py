#!/usr/bin/env python
"""Comm/compute overlap study on the Jacobi-3D step.

Reference parity: bin/measure_buf_exchange.cu (overlap study with a
clock-spin kernel riding alongside the exchange). The TPU analog times
four programs at the same size — exchange only, fused step (exchange +
stencil in program order), overlapped step (interior split off the
exchange's data dependencies) — and reports how much of the exchange
the overlapped schedule hides:

    overlap_efficiency = (t_fused - t_overlap) / t_exchange
"""

import argparse

from _common import add_device_flags, apply_device_flags, csv_line, timed_samples


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=64, help="per-device x size")
    ap.add_argument("--y", type=int, default=64)
    ap.add_argument("--z", type=int, default=64)
    ap.add_argument("--iters", "-n", type=int, default=20)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax
    import numpy as np

    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.parallel.mesh import default_mesh_shape
    from stencil_tpu.utils.timers import device_sync

    ndev = len(jax.devices())
    mesh_shape = default_mesh_shape(ndev)
    gx, gy, gz = (args.x * mesh_shape.x, args.y * mesh_shape.y,
                  args.z * mesh_shape.z)

    results = {}
    fused = Jacobi3D(gx, gy, gz, mesh_shape=mesh_shape, dtype=np.float32)
    fused.init()
    stats = timed_samples(fused.step, fused.block, args.iters)
    results["fused"] = stats.trimean()

    dd = fused.dd
    stats = timed_samples(dd.exchange, lambda: device_sync(dd.curr),
                          args.iters)
    results["exchange_only"] = stats.trimean()

    over = Jacobi3D(gx, gy, gz, mesh_shape=mesh_shape, dtype=np.float32,
                    overlap=True)
    over.init()
    stats = timed_samples(over.step, over.block, args.iters)
    results["overlap"] = stats.trimean()

    hidden = results["fused"] - results["overlap"]
    eff = hidden / results["exchange_only"] if results["exchange_only"] else 0.0
    print(csv_line("measure_overlap", ndev, gx, gy, gz,
                   f"{results['exchange_only']:.6e}",
                   f"{results['fused']:.6e}",
                   f"{results['overlap']:.6e}",
                   f"{eff:.3f}"))


if __name__ == "__main__":
    main()
