#!/usr/bin/env python
"""Pure exchange() time, strong scaling (fixed global size)
(reference: bin/exchange_strong.cu)."""

import argparse

from _common import (add_device_flags, apply_device_flags,
                     add_method_flags, methods_from_args)
from exchange_weak import run_exchange_bench


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=512, help="global x size")
    ap.add_argument("--y", type=int, default=512)
    ap.add_argument("--z", type=int, default=512)
    ap.add_argument("--radius", type=int, default=3)
    ap.add_argument("--fields", type=int, default=1)
    ap.add_argument("--iters", "-n", type=int, default=30)
    ap.add_argument("--interior-slabs", action="store_true",
                    help="measure the fused fast paths' interior-"
                         "resident slab exchange instead of the padded "
                         "orchestrator exchange (x-unsharded mesh)")
    add_method_flags(ap)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    mesh_shape = None
    if args.interior_slabs:
        import jax

        from stencil_tpu.parallel.mesh import default_mesh_shape_xfree
        mesh_shape = default_mesh_shape_xfree(len(jax.devices()))
    run_exchange_bench("exchange_strong", args.x, args.y, args.z,
                       mesh_shape, args.radius, args.fields, args.iters,
                       methods_from_args(args),
                       interior_slabs=args.interior_slabs)


if __name__ == "__main__":
    main()
