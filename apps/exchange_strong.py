#!/usr/bin/env python
"""Pure exchange() time, strong scaling (fixed global size)
(reference: bin/exchange_strong.cu)."""

import argparse

from _common import (add_device_flags, apply_device_flags,
                     add_method_flags, methods_from_args)
from exchange_weak import run_exchange_bench


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=512, help="global x size")
    ap.add_argument("--y", type=int, default=512)
    ap.add_argument("--z", type=int, default=512)
    ap.add_argument("--radius", type=int, default=3)
    ap.add_argument("--fields", type=int, default=1)
    ap.add_argument("--iters", "-n", type=int, default=30)
    add_method_flags(ap)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    run_exchange_bench("exchange_strong", args.x, args.y, args.z, None,
                       args.radius, args.fields, args.iters,
                       methods_from_args(args))


if __name__ == "__main__":
    main()
