#!/usr/bin/env python
"""Weak-scaling Jacobi-3D heat demo.

Reference parity: bin/jacobi3d.cu — the global grid is the per-device
size scaled by the subdomain grid (weak scaling,
bin/jacobi3d.cu:181-205); CSV result line
``bin,methods,devices,x,y,z,bytes_x,bytes_y,bytes_z,min (s),trimean (s)``
(schema analog of bin/jacobi3d.cu:383-392).
"""

import argparse
import os
import sys

from _common import (KERNEL_CHOICES, add_bench_record_flags,
                     add_dcn_flags, add_device_flags, add_dtype_flags,
                     add_method_flags, add_placement_flags,
                     apply_device_flags, csv_line, dcn_from_args,
                     dcn_mesh_shape, dtype_from_args,
                     emit_bench_artifacts, methods_from_args,
                     placement_from_args, sampled_steps_per_s)


def _run_resilient(j, args) -> None:
    """The chaos-smoke entry: drive the solver under the recovery
    driver with the seeded faults from the --chaos-* flags, then emit
    a summary line and (optionally) the event-log JSON artifact."""
    from stencil_tpu.resilience import (FaultPlan, HaloCorruption,
                                        NaNInjection, Preemption,
                                        ResiliencePolicy,
                                        TransientSaveFailure)

    plan = FaultPlan(seed=args.chaos_seed)
    if args.chaos_nan:
        plan.nans.append(NaNInjection(step=args.chaos_nan))
    if args.chaos_halo:
        plan.halos.append(HaloCorruption(step=args.chaos_halo))
    if args.chaos_save_fail:
        plan.save_failures.append(
            TransientSaveFailure(step=args.chaos_save_fail))
    if args.chaos_preempt:
        plan.preemptions.append(Preemption(step=args.chaos_preempt))
    policy = ResiliencePolicy(check_every=args.check_every,
                              ckpt_every=args.ckpt_every,
                              max_retries=args.max_retries,
                              base_delay=0.01,
                              fuse_segments=args.fuse_segments)
    report = j.run_resilient(args.iters, policy=policy,
                             ckpt_dir=args.ckpt_dir or None,
                             faults=plan)
    if args.events_json:
        report.write(args.events_json)
    print(csv_line("jacobi3d-resilient", methods_label(args),
                   report.steps, report.rollbacks, report.save_retries,
                   len(report.degradations),
                   int(report.preempted), report.final_config))


def methods_label(args) -> str:
    return str(methods_from_args(args))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=128, help="per-device x size")
    ap.add_argument("--y", type=int, default=128)
    ap.add_argument("--z", type=int, default=128)
    ap.add_argument("--iters", "-n", type=int, default=30)
    ap.add_argument("--batch", type=int, default=10,
                    help="iterations per timing sample (fused loop)")
    ap.add_argument("--prefix", default="", help="output prefix")
    ap.add_argument("--json-out", default="", metavar="PATH",
                    help="write the timed run's bench record (steps/s "
                         "+ byte model) as a JSON artifact")
    ap.add_argument("--paraview", action="store_true")
    ap.add_argument("--period", type=int, default=0,
                    help="paraview dump every N samples")
    add_dtype_flags(ap)
    ap.add_argument("--wrap-steps", type=int, default=0, metavar="N",
                    help="temporal-blocking depth for the fused wrap "
                         "and halo paths (N fused iterations per HBM "
                         "pass / exchange; default 2)")
    ap.add_argument("--kernel", default="auto", choices=KERNEL_CHOICES,
                    help="compute path: fused Pallas (wrap: single-chip "
                         "periodic; halo: multi-chip slab layout), XLA "
                         "slicing (xla), padded-layout Pallas (pallas), "
                         "or pick by hardware (auto)")
    ap.add_argument("--exchange-every", type=int, default=0, metavar="S",
                    help="communication-avoiding temporal blocking: one "
                         "depth-S halo exchange per S iterations (the "
                         "XLA path fuses S sub-steps on shrinking "
                         "windows; the wrap/halo fast paths set their "
                         "in-kernel step count to S)")
    add_method_flags(ap)
    add_placement_flags(ap)
    add_dcn_flags(ap)
    add_device_flags(ap)
    add_bench_record_flags(ap)
    res = ap.add_argument_group(
        "resilience", "run under the checkpoint-rollback recovery "
        "driver (stencil_tpu/resilience); the --chaos-* flags inject "
        "seeded faults so recovery paths can be smoked in CI")
    res.add_argument("--resilient", action="store_true",
                     help="run --iters iterations under run_resilient "
                          "instead of the timed benchmark loop")
    res.add_argument("--ckpt-dir", default="",
                     help="checkpoint/resume directory (preempted runs "
                          "resume from it on the next invocation)")
    res.add_argument("--ckpt-every", type=int, default=10)
    res.add_argument("--check-every", type=int, default=1,
                     help="health-sentinel boundary cadence (steps); "
                          "with --fuse-segments this is also the "
                          "megastep segment length")
    res.add_argument("--fuse-segments",
                     action=argparse.BooleanOptionalAction,
                     default=True,
                     help="megastep execution (default on): dispatch "
                          "ONE fused program per check_every boundary "
                          "with the health probe trace in-graph "
                          "(parallel/megastep.py); "
                          "--no-fuse-segments restores the per-step "
                          "dispatch loop")
    res.add_argument("--max-retries", type=int, default=3)
    res.add_argument("--events-json", default="",
                     help="write the resilience event log (JSON) here")
    res.add_argument("--chaos-nan", type=int, default=0, metavar="STEP",
                     help="inject one NaN into shard 0 after STEP")
    res.add_argument("--chaos-halo", type=int, default=0, metavar="STEP",
                     help="corrupt a halo cell after STEP")
    res.add_argument("--chaos-save-fail", type=int, default=0,
                     metavar="STEP", help="the checkpoint save at STEP "
                     "raises transient IOErrors (retried)")
    res.add_argument("--chaos-preempt", type=int, default=0,
                     metavar="STEP", help="deliver SIGTERM after STEP")
    res.add_argument("--chaos-seed", type=int, default=0)
    args = ap.parse_args()
    apply_device_flags(args)
    dtype = dtype_from_args(args)

    import jax

    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.ops.pallas_stencil import on_tpu
    from stencil_tpu.parallel.mesh import (default_mesh_shape,
                                           default_mesh_shape_xfree)

    ndev = len(jax.devices())
    # halo-capable paths want the lane (x) axis unsharded; "auto" only
    # selects them on TPU, so keep the cube-like mesh off-TPU
    xfree = (args.kernel == "halo"
             or (args.kernel == "auto" and on_tpu()))
    mesh_shape = (dcn_mesh_shape(args, xfree)
                  or (default_mesh_shape_xfree(ndev) if xfree
                      else default_mesh_shape(ndev)))
    # weak scaling: global = local x mesh (bin/jacobi3d.cu:181-205)
    gx, gy, gz = (args.x * mesh_shape.x, args.y * mesh_shape.y,
                  args.z * mesh_shape.z)
    methods = methods_from_args(args)
    if args.wrap_steps:
        os.environ["STENCIL_WRAP_STEPS"] = str(args.wrap_steps)
    j = Jacobi3D(gx, gy, gz, mesh_shape=mesh_shape,
                 dtype=dtype,
                 methods=methods,
                 placement=placement_from_args(args),
                 output_prefix=args.prefix, kernel=args.kernel,
                 exchange_every=args.exchange_every or None,
                 **dcn_from_args(args))
    j.init()
    if args.paraview:
        j.dd.write_paraview(args.prefix + "jacobi3d_init")

    if args.resilient:
        _run_resilient(j, args)
        return

    samples = max(args.iters // args.batch, 1)
    n = 0

    def one():
        nonlocal n
        j.run(args.batch)
        n += 1
        if args.paraview and args.period and n % args.period == 0:
            j.dd.write_paraview(f"{args.prefix}jacobi3d_{n}")

    # the one shared warmup/measure/block contract (_common)
    stats, sps = sampled_steps_per_s(one, j.block, samples, args.batch)
    b = j.dd.exchange_bytes_per_axis()
    # honest exchange-cost estimate for the built path (the fused fast
    # paths never call dd.exchange(); see Jacobi3D.exchange_stats):
    # exchange seconds and wire bytes per ITERATION
    xstats = j.exchange_stats()
    ex_s = j.measure_exchange_seconds()
    print(csv_line("jacobi3d", methods, ndev, gx, gy, gz,
                   b["x"], b["y"], b["z"],
                   f"{stats.min() / args.batch:.6e}",
                   f"{stats.trimean() / args.batch:.6e}",
                   xstats["path"], int(xstats["bytes_per_iteration"]),
                   f"{ex_s:.6e}"))
    # tiling-plan provenance for the ledger: when a Pallas kernel path
    # ran, record the block shapes the VMEM planner prescribed for this
    # shard geometry (observatory records then group real-TPU numbers
    # against the shapes that produced them)
    tiling_plan = None
    if "xla" not in xstats["path"]:
        try:
            from stencil_tpu.parallel.mesh import mesh_dim
            from stencil_tpu.tuning import (geometry_from_domain,
                                            tiling_record)

            tiling_plan = tiling_record(
                geometry_from_domain(j.dd, mesh_dim(j.dd.mesh)))
        except Exception as e:  # noqa: BLE001 — provenance best-effort
            print(f"jacobi3d: tiling provenance unavailable: {e}",
                  file=sys.stderr)
    emit_bench_artifacts(
        args,
        {"bench": "jacobi3d",
         **({"tiling_plan": tiling_plan} if tiling_plan else {}),
         "config": {"grid": [gx, gy, gz], "devices": ndev,
                    "mesh": list(mesh_shape), "kernel": xstats["path"],
                    "methods": str(methods),
                    "dtype": str(dtype.__name__ if hasattr(dtype, "__name__")
                                 else dtype),
                    "exchange_every": args.exchange_every or 1},
         "metrics": {"steps_per_s": sps,
                     "min_step_s": stats.min() / args.batch,
                     "trimean_step_s": stats.trimean() / args.batch,
                     "bytes_per_iteration_model":
                         float(xstats["bytes_per_iteration"]),
                     "exchange_s_per_iteration": ex_s}},
        "jacobi3d")
    if args.paraview:
        j.dd.write_paraview(args.prefix + "jacobi3d_final")


if __name__ == "__main__":
    main()
