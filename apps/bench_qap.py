#!/usr/bin/env python
"""QAP placement-solver timing vs matrix size
(reference: bin/bench_qap.cu:1-13)."""

import argparse
import time

from _common import add_device_flags, apply_device_flags, csv_line


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", type=int, nargs="+",
                    default=[4, 6, 8, 10, 16, 32])
    ap.add_argument("--timeout", type=float, default=2.0)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import numpy as np

    from stencil_tpu import qap

    rng = np.random.default_rng(0)
    print(csv_line("bench_qap", "n", "native", "exact_s", "exact_cost",
                   "catch_s", "catch_cost"))
    for n in args.sizes:
        w = rng.uniform(0, 10, (n, n))
        np.fill_diagonal(w, 0)
        d = rng.uniform(0.1, 1, (n, n))
        np.fill_diagonal(d, 0)
        if n <= 10:
            t0 = time.perf_counter()
            _, c_exact = qap.solve(w, d, timeout_s=args.timeout)
            t_exact = time.perf_counter() - t0
        else:
            t_exact, c_exact = float("nan"), float("nan")
        t0 = time.perf_counter()
        _, c_catch = qap.solve_catch(w, d)
        t_catch = time.perf_counter() - t0
        print(csv_line("bench_qap", n, qap.native_available(),
                       f"{t_exact:.4f}", f"{c_exact:.3f}",
                       f"{t_catch:.4f}", f"{c_catch:.3f}"))


if __name__ == "__main__":
    main()
