#!/usr/bin/env python
"""Astaroth-style MHD mini-app CLI.

Reference parity: astaroth/astaroth.cu main — conf-file loading,
iteration loop, CSV line ``devices,nx,ny,nz,iter trimean,exch trimean``
(reference: astaroth/astaroth.cu:668-676).
"""

import argparse

from _common import (add_dcn_flags, add_device_flags, add_dtype_flags,
                     apply_device_flags,
                     add_method_flags, csv_line, dcn_from_args,
                     dtype_from_args,
                     dcn_mesh_shape, methods_from_args, timed_samples)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--conf", default="", help="astaroth.conf-style file")
    ap.add_argument("--nx", type=int, default=64, help="per-device x size")
    ap.add_argument("--ny", type=int, default=64)
    ap.add_argument("--nz", type=int, default=64)
    ap.add_argument("--iters", "-n", type=int, default=10)
    add_dtype_flags(ap)
    ap.add_argument("--paraview-init", action="store_true")
    ap.add_argument("--paraview-final", action="store_true")
    ap.add_argument("--prefix", default="")
    ap.add_argument("--exchange-every", type=int, default=0, metavar="S",
                    help="communication-avoiding temporal blocking: one "
                         "depth-(S*R) exchange per S RK substeps "
                         "(multiples of 3 keep the w accumulator off "
                         "the wire; S=2 maps to the fused substep-0+1 "
                         "kernel on the Pallas halo path)")
    ap.add_argument("--overlap", action="store_true",
                    help="interior/exterior comm-compute overlap per substep")
    ap.add_argument("--fuse-segments",
                    action=argparse.BooleanOptionalAction, default=False,
                    help="megastep execution: advance --check-every "
                         "iterations per dispatch as ONE fused program "
                         "with the health probe trace in-graph "
                         "(parallel/megastep.py; XLA and temporal "
                         "paths fuse — the temporal path chunks whole "
                         "lcm(3, s)-period groups with the w carry "
                         "donated; the interior-resident Pallas fast "
                         "paths decline loudly and keep the classic "
                         "loop)")
    ap.add_argument("--check-every", type=int, default=4,
                    help="megastep segment length (iterations per "
                         "fused dispatch) for --fuse-segments")
    ap.add_argument("--kernel", default="auto",
                    choices=("auto", "wrap", "halo", "xla"),
                    help="compute path: fused Pallas megakernel (wrap: "
                         "single-chip; halo: multi-chip slab layout), "
                         "XLA slicing (xla), or pick by hardware (auto)")
    ap.add_argument("--checkpoint-dir", default="",
                    help="checkpoint directory (the working AC_start_step "
                         "analog — the reference's conf knob is never "
                         "restored, astaroth/astaroth.conf:36-38)")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="save every N iterations (0: only at exit)")
    ap.add_argument("--resume", action="store_true",
                    help="resume from the latest checkpoint in "
                         "--checkpoint-dir")
    add_method_flags(ap)
    add_dcn_flags(ap)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)
    dtype = dtype_from_args(args)

    import jax
    import numpy as np

    from stencil_tpu.models.astaroth import Astaroth, MhdParams
    from stencil_tpu.ops.pallas_stencil import on_tpu
    from stencil_tpu.parallel.mesh import (default_mesh_shape,
                                           default_mesh_shape_xfree)

    prm = MhdParams.from_conf(args.conf) if args.conf else MhdParams()
    ndev = len(jax.devices())
    # halo-capable paths (including the in-kernel RDMA overlap) want the
    # lane (x) axis unsharded; "auto" only selects them on TPU, so keep
    # the cube-like mesh off-TPU
    xfree = (args.kernel == "halo"
             or (args.kernel == "auto" and on_tpu()))
    mesh_shape = (dcn_mesh_shape(args, xfree)
                  or (default_mesh_shape_xfree(ndev) if xfree
                      else default_mesh_shape(ndev)))
    gx = args.nx * mesh_shape.x
    gy = args.ny * mesh_shape.y
    gz = args.nz * mesh_shape.z
    m = Astaroth(gx, gy, gz, params=prm, mesh_shape=mesh_shape,
                 dtype=dtype,
                 methods=methods_from_args(args), overlap=args.overlap,
                 kernel=args.kernel,
                 exchange_every=args.exchange_every or None,
                 **dcn_from_args(args))
    m.init()
    start_iter = 0
    if args.checkpoint_dir and args.resume:
        from stencil_tpu.utils.checkpoint import restore_domain
        m.sync_domain()   # flush + drop the interior-resident cache so
        # the restored dd.curr is what the next iteration extracts
        start_iter, extra = restore_domain(m.dd, args.checkpoint_dir)
        if extra:
            m._w = extra
        print(f"# resumed from step {start_iter}")
    if args.paraview_init:
        m.dd.write_paraview(args.prefix + "init")

    # count every iteration actually taken (warmups included) so saved
    # step numbers always match the integrated state
    it = start_iter
    last_saved = None

    segment = None
    if args.fuse_segments:
        segment = m.make_segment(max(args.check_every, 1))
        if not segment:
            import sys
            reason = getattr(segment, "reason", "no fused-segment "
                             "support")
            print("# --fuse-segments: declined on the "
                  f"'{m.kernel_path}' path ({reason}); using the "
                  "classic loop", file=sys.stderr)
            segment = None

    def counted_step():
        nonlocal it, last_saved
        prev = it
        if segment is not None:
            # one fused dispatch advances check_every iterations with
            # the in-graph probe trace (discarded here — the timed
            # sample measures the production megastep as dispatched)
            segment.run(it)
            it += segment.steps
        else:
            m.step()
            it += 1
        # "crossed a checkpoint boundary" rather than an exact modulus:
        # a fused sample advances several iterations at once, and the
        # requested cadence must not silently skip when check_every
        # does not divide checkpoint_every
        if (args.checkpoint_dir and args.checkpoint_every
                and it // args.checkpoint_every
                > prev // args.checkpoint_every):
            from stencil_tpu.utils.checkpoint import save_domain
            m.sync_domain()
            save_domain(m.dd, args.checkpoint_dir, it, extra=m._w)
            last_saved = it

    samples = (max(args.iters // segment.steps, 1)
               if segment is not None else args.iters)
    stats = timed_samples(counted_step, m.block, samples)
    if args.checkpoint_dir and last_saved != it:
        from stencil_tpu.utils.checkpoint import save_domain
        m.sync_domain()
        save_domain(m.dd, args.checkpoint_dir, it, extra=m._w)

    # exchange-only estimate, path-aware: the fused halo path performs
    # slab rounds inside its jitted loop (never dd.exchange()), so the
    # standalone measurement times exactly that transfer; xla paths
    # time the orchestrator exchange. Per-iteration seconds + wire
    # bytes (reference CSV: astaroth.cu:668-676 iter/exch trimeans).
    exch = m.measure_exchange_seconds()
    xstats = m.exchange_stats()

    if args.paraview_final:
        # flush the interior-resident fast-path state into dd.curr —
        # without this the dump would be the initial condition
        m.sync_domain()
        m.dd.write_paraview(args.prefix + "final")
    # per-ITERATION trimean regardless of dispatch granularity (a
    # fused segment sample covers segment.steps iterations)
    per_iter = stats.trimean() / (segment.steps if segment is not None
                                  else 1)
    print(csv_line(ndev, gx, gy, gz,
                   f"{per_iter:.6e}", f"{exch:.6e}",
                   xstats["path"], int(xstats["bytes_per_iteration"])))


if __name__ == "__main__":
    main()
