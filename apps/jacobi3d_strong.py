#!/usr/bin/env python
"""Strong-scaling Jacobi-3D: fixed global size over all devices
(reference: bin/jacobi3d_strong.cu)."""

import argparse

from _common import (KERNEL_CHOICES, add_device_flags, add_dtype_flags,
                     apply_device_flags, add_method_flags,
                     add_placement_flags, csv_line, dtype_from_args,
                     methods_from_args, placement_from_args, timed_samples)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=512, help="global x size")
    ap.add_argument("--y", type=int, default=512)
    ap.add_argument("--z", type=int, default=512)
    ap.add_argument("--iters", "-n", type=int, default=30)
    ap.add_argument("--batch", type=int, default=10)
    add_dtype_flags(ap)
    ap.add_argument("--kernel", default="auto", choices=KERNEL_CHOICES)
    add_method_flags(ap)
    add_placement_flags(ap)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)
    dtype = dtype_from_args(args)

    import jax

    from stencil_tpu.models.jacobi import Jacobi3D

    ndev = len(jax.devices())
    methods = methods_from_args(args)
    j = Jacobi3D(args.x, args.y, args.z,
                 dtype=dtype,
                 methods=methods, kernel=args.kernel,
                 placement=placement_from_args(args))
    j.init()
    samples = max(args.iters // args.batch, 1)
    stats = timed_samples(lambda: j.run(args.batch), j.block, samples)
    b = j.dd.exchange_bytes_per_axis()
    # honest exchange estimate for the built path (fast paths bypass
    # dd.exchange(); see Jacobi3D.exchange_stats)
    xstats = j.exchange_stats()
    ex_s = j.measure_exchange_seconds()
    print(csv_line("jacobi3d_strong", methods, ndev,
                   args.x, args.y, args.z, b["x"], b["y"], b["z"],
                   f"{stats.min() / args.batch:.6e}",
                   f"{stats.trimean() / args.batch:.6e}",
                   xstats["path"], int(xstats["bytes_per_iteration"]),
                   f"{ex_s:.6e}"))


if __name__ == "__main__":
    main()
