#!/usr/bin/env python
"""Strong-scaling Jacobi-3D: fixed global size over all devices
(reference: bin/jacobi3d_strong.cu)."""

import argparse

from _common import (add_device_flags, apply_device_flags,
                     add_method_flags, add_placement_flags, csv_line,
                     methods_from_args, placement_from_args, timed_samples)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=512, help="global x size")
    ap.add_argument("--y", type=int, default=512)
    ap.add_argument("--z", type=int, default=512)
    ap.add_argument("--iters", "-n", type=int, default=30)
    ap.add_argument("--batch", type=int, default=10)
    ap.add_argument("--f64", action="store_true")
    add_method_flags(ap)
    add_placement_flags(ap)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)
    if getattr(args, 'f64', False):
        import jax
        jax.config.update('jax_enable_x64', True)

    import jax
    import numpy as np

    from stencil_tpu.models.jacobi import Jacobi3D

    ndev = len(jax.devices())
    methods = methods_from_args(args)
    j = Jacobi3D(args.x, args.y, args.z,
                 dtype=np.float64 if args.f64 else np.float32,
                 methods=methods,
                 placement=placement_from_args(args))
    j.init()
    samples = max(args.iters // args.batch, 1)
    stats = timed_samples(lambda: j.run(args.batch), j.block, samples)
    b = j.dd.exchange_bytes_per_axis()
    print(csv_line("jacobi3d_strong", methods, ndev,
                   args.x, args.y, args.z, b["x"], b["y"], b["z"],
                   f"{stats.min() / args.batch:.6e}",
                   f"{stats.trimean() / args.batch:.6e}"))


if __name__ == "__main__":
    main()
