"""Fleet chaos smoke front end (stencil_tpu/serving/fleet).

Drives a multi-replica serving fleet end to end and exports the
artifacts CI gates on: per-campaign sha256 digests of the final field
(the bitwise zero-loss comparison between a calm and a chaos run),
the fleet event log, the fleet metrics snapshot, and every replica's
metrics snapshot. Chaos is deterministic and declared on the command
line: kill a replica mid-batch (``--kill-replica``), flood admission
with low-priority junk (``--flood``), or both.

Examples:
  # calm reference run
  python fleet.py --replicas 3 --tenants 4 --fake-cpu 8 --fake-timer \\
      --tune-cache plans.json --results-json calm.json
  # chaos run against the same plan cache: kill + flood
  python fleet.py --replicas 3 --tenants 4 --fake-cpu 8 --fake-timer \\
      --tune-cache plans.json --kill-replica 1 --kill-at-step 2 \\
      --flood 6 --max-queue-depth 3 --results-json chaos.json \\
      --events-json events.json --metrics-json metrics.json
"""

import argparse
import hashlib
import json
import shutil
import sys
import tempfile

from _common import add_device_flags, apply_device_flags


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_device_flags(p)
    p.add_argument("--model", choices=("jacobi", "astaroth"),
                   default="jacobi")
    p.add_argument("--x", type=int, default=8)
    p.add_argument("--y", type=int, default=8)
    p.add_argument("--z", type=int, default=8)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--tenants", type=int, default=4,
                   help="concurrent fake tenants (t0..tN-1)")
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--width", type=int, default=4,
                   help="per-replica ensemble width")
    p.add_argument("--ckpt-every", type=int, default=2)
    p.add_argument("--kill-replica", type=int, default=-1,
                   metavar="R",
                   help="hard-crash this replica index mid-batch "
                        "(chaos round 0; -1 = no crash; 'auto' via "
                        "--kill-owner-of)")
    p.add_argument("--kill-owner-of", default="", metavar="TENANT",
                   help="instead of an index, kill whichever replica "
                        "the rendezvous hash routes TENANT to — "
                        "guarantees the victim owns >= 1 campaign")
    p.add_argument("--kill-at-step", type=int, default=2,
                   metavar="STEP",
                   help="member step the armed crash fires at (after "
                        "that step's boundary work, checkpoints "
                        "included, has landed)")
    p.add_argument("--flood", type=int, default=0, metavar="N",
                   help="submit N priority-0 junk requests at chaos "
                        "round 0 (drives the shed path)")
    p.add_argument("--max-queue-depth", type=int, default=64,
                   help="SLO policy: shed sub-protected work when a "
                        "replica's exported queue depth reaches this")
    p.add_argument("--root", default="",
                   help="shared checkpoint root (default: tmpdir)")
    p.add_argument("--keep-root", action="store_true")
    p.add_argument("--fake-timer", action="store_true",
                   help="tune exchange plans with the deterministic "
                        "FakeTimer (CI: no hardware dependence)")
    p.add_argument("--tune-cache", default="",
                   help="shared tuning-plan cache path; point the "
                        "calm and chaos runs at ONE file so no chaos "
                        "replica ever re-measures")
    p.add_argument("--flight-dir", default="", metavar="DIR",
                   help="flight-recorder dump directory for every "
                        "replica (black-box dumps on crash)")
    p.add_argument("--results-json", default="", metavar="PATH",
                   help="write per-campaign digests + per-replica "
                        "metric readbacks here (the CI bitwise "
                        "artifact)")
    p.add_argument("--events-json", default="", metavar="PATH",
                   help="write the fleet event log here")
    p.add_argument("--metrics-json", default="", metavar="PATH",
                   help="write the fleet metrics snapshot here")
    args = p.parse_args()
    apply_device_flags(args)

    import numpy as np

    from stencil_tpu.resilience.faults import AdmissionFlood, ReplicaCrash
    from stencil_tpu.serving import (CampaignRequest, Fleet, SloPolicy,
                                     rendezvous_replica)
    from stencil_tpu.serving.queue import request_fingerprint
    from stencil_tpu.tuning import FakeTimer

    def request(tenant: str, seed: int) -> CampaignRequest:
        params = ({"hot_temp": 1.0 + 0.05 * seed}
                  if args.model == "jacobi" else
                  {"nu_visc": 5e-3 * (1.0 + 0.1 * seed)})
        return CampaignRequest(
            tenant=tenant, campaign="c0", model=args.model,
            grid=(args.x, args.y, args.z), n_steps=args.steps,
            ckpt_every=args.ckpt_every, init_seed=100 + seed,
            params=params)

    tenants = [f"t{i}" for i in range(args.tenants)]
    reqs = [request(t, i) for i, t in enumerate(tenants)]

    victim = args.kill_replica
    if args.kill_owner_of:
        names = [f"replica-{i}" for i in range(args.replicas)]
        fp = request_fingerprint(request(args.kill_owner_of, 0))
        owner = rendezvous_replica(f"{fp}|{args.kill_owner_of}", names)
        victim = int(owner.rsplit("-", 1)[1])
    chaos = []
    if victim >= 0:
        chaos.append(ReplicaCrash(step=0, replica=victim,
                                  at_member_step=args.kill_at_step))
        print(f"chaos: kill replica-{victim} at member step "
              f"{args.kill_at_step}", file=sys.stderr)
    if args.flood > 0:
        chaos.append(AdmissionFlood(step=0, tenant="flood",
                                    count=args.flood, priority=0,
                                    n_steps=1))
        print(f"chaos: flood {args.flood} priority-0 requests",
              file=sys.stderr)

    root = args.root or tempfile.mkdtemp(prefix="fleet_root.")
    fl = Fleet(
        root, n_replicas=args.replicas, width=args.width,
        tuner_timer=FakeTimer() if args.fake_timer else None,
        plan_cache_path=args.tune_cache or None,
        policy=SloPolicy(max_queue_depth=args.max_queue_depth),
        chaos=chaos,
        flight_recorder_dir=args.flight_dir or None)

    # artifacts export on the FAILURE path too — a lost campaign is
    # exactly when the event log and digests are needed
    results = {"run": fl.run_id, "killed": victim if victim >= 0 else None,
               "campaigns": {}, "replicas": {}}
    try:
        handles = [fl.submit(r) for r in reqs]
        fl.serve()
        for t, h in zip(tenants, handles):
            if not h.done():
                results["campaigns"][t] = {"ok": False,
                                           "error": "lost (unresolved)"}
                continue
            try:
                r = h.result(timeout=0)
            except Exception as e:  # noqa: BLE001 - recorded, gated in CI
                results["campaigns"][t] = {
                    "ok": False, "error": f"{type(e).__name__}: {e}"}
                continue
            field = np.ascontiguousarray(
                np.asarray(next(iter(r.final.values()))))
            results["campaigns"][t] = {
                "ok": True, "steps": r.steps,
                "resumed_from": r.resumed_from,
                "digest": hashlib.sha256(field.tobytes()).hexdigest()}
            print(f"{r.tenant}/{r.campaign}: steps={r.steps} "
                  f"resumed_from={r.resumed_from} "
                  f"digest={results['campaigns'][t]['digest'][:12]}")
        # per-replica readbacks come off the EXPORTED metrics text —
        # the same surface an external scraper would gate on
        from stencil_tpu.telemetry import metric_value
        for rep in fl.replicas:
            text = rep.service.metrics_text()
            results["replicas"][rep.name] = {
                "state": rep.state,
                "batches": metric_value(
                    text, "stencil_service_batches_total"),
                "compiles": metric_value(
                    text, "stencil_service_compiles_total"),
                "recompiles": metric_value(
                    text, "stencil_service_recompiles_total"),
                "tuner_measurements": metric_value(
                    text, "stencil_service_tuner_measurements_total"),
                "metrics": rep.service.metrics.snapshot()}
        results["fleet_metrics"] = fl.metrics_snapshot()
        states = [r["state"] for r in results["replicas"].values()]
        print(f"fleet: replicas={states} "
              f"campaigns_ok="
              f"{sum(1 for c in results['campaigns'].values() if c['ok'])}"
              f"/{len(tenants)}")
    finally:
        def attempt(what, fn) -> None:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - report, don't mask
                print(f"warning: {what} failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)

        if args.results_json:
            attempt("results export", lambda: (
                json.dump(results, open(args.results_json, "w"),
                          indent=1),
                print(f"results -> {args.results_json}",
                      file=sys.stderr)))
        if args.events_json:
            attempt("event log export", lambda: (
                fl.write_events(args.events_json),
                print(f"event log -> {args.events_json}",
                      file=sys.stderr)))
        if args.metrics_json:
            attempt("metrics snapshot export", lambda: (
                fl.metrics.write_snapshot(args.metrics_json),
                print(f"metrics snapshot -> {args.metrics_json}",
                      file=sys.stderr)))
        if not args.root and not args.keep_root:
            shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
