#!/usr/bin/env python
"""Weak-scaling particle-in-cell demo (models/pic.py).

The dynamic-communication counterpart of jacobi3d.py: charged
particles deposit onto the sharded grid (reverse halo-accumulate),
gather the field, push, and MIGRATE between shards over the
fixed-capacity ppermute ring each step. CSV result line
``pic,methods,devices,x,y,z,particles,deposition,min (s),trimean (s),
particle_steps_per_s,mig_bytes_per_shard,overflow``; --resilient runs
under the recovery driver with the --chaos-* fault plan (ParticleLoss
included) — the CI pic-smoke stage's entry point.
"""

import argparse

from _common import (add_bench_record_flags, add_device_flags,
                     add_dtype_flags, add_method_flags,
                     apply_device_flags, csv_line, dtype_from_args,
                     emit_bench_artifacts, methods_from_args,
                     sampled_steps_per_s)


def _run_resilient(p, args) -> None:
    from stencil_tpu.resilience import (FaultPlan, NaNInjection,
                                        ParticleLoss, ResiliencePolicy,
                                        TransientSaveFailure)

    plan = FaultPlan(seed=args.chaos_seed)
    if args.chaos_particle_loss:
        plan.particle_losses.append(
            ParticleLoss(step=args.chaos_particle_loss,
                         count=args.chaos_particle_count))
    if args.chaos_nan:
        plan.nans.append(NaNInjection(step=args.chaos_nan))
    if args.chaos_save_fail:
        plan.save_failures.append(
            TransientSaveFailure(step=args.chaos_save_fail))
    policy = ResiliencePolicy(check_every=args.check_every,
                              ckpt_every=args.ckpt_every,
                              max_retries=args.max_retries,
                              base_delay=0.01,
                              fuse_segments=args.fuse_segments)
    report = p.run_resilient(args.iters, policy=policy,
                             ckpt_dir=args.ckpt_dir or None,
                             faults=plan)
    if args.events_json:
        report.write(args.events_json)
    print(csv_line("pic-resilient", methods_from_args(args),
                   report.steps, report.rollbacks, report.save_retries,
                   int(report.preempted), int(p.overflow_total()),
                   report.final_config))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=8, help="per-device x size")
    ap.add_argument("--y", type=int, default=8)
    ap.add_argument("--z", type=int, default=8)
    ap.add_argument("--particles", type=int, default=512, metavar="N",
                    help="particles per DEVICE (weak scaling)")
    ap.add_argument("--iters", "-n", type=int, default=20)
    ap.add_argument("--batch", type=int, default=5,
                    help="iterations per timing sample (fused loop)")
    ap.add_argument("--deposition", choices=("cic", "ngp"),
                    default="cic")
    ap.add_argument("--dt", type=float, default=0.25)
    ap.add_argument("--capacity", type=int, default=0,
                    help="per-shard particle slots (0 = 2x mean fill)")
    ap.add_argument("--budget", type=int, default=0,
                    help="migration record slots per direction "
                         "(0 = capacity/4)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fuse-segments",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="megastep execution (default on): the bench "
                         "path races ONE fused dispatch per "
                         "--fuse-check-every steps (probe trace "
                         "in-graph) against the per-step "
                         "dispatch+probe loop and records the ratio; "
                         "--resilient runs the recovery driver fused")
    ap.add_argument("--fuse-check-every", type=int, default=8,
                    help="megastep segment length for the bench race")
    ap.add_argument("--json-out", default="",
                    help="write the bench record (BENCH_pr10 schema)")
    ap.add_argument("--metrics-json", default="", metavar="PATH",
                    help="also record the measured numbers as a "
                         "telemetry metrics snapshot (gauges "
                         "stencil_bench_particle_steps_per_s{deposition"
                         "=} and stencil_bench_migration_bytes_per_"
                         "shard{deposition=}) so the JSON artifact and "
                         "the metrics surface agree on one figure")
    add_dtype_flags(ap)
    add_method_flags(ap)
    add_device_flags(ap)
    add_bench_record_flags(ap)
    res = ap.add_argument_group(
        "resilience", "run under the checkpoint-rollback driver; the "
        "--chaos-* flags inject seeded faults (CI pic-smoke)")
    res.add_argument("--resilient", action="store_true")
    res.add_argument("--ckpt-dir", default="")
    res.add_argument("--ckpt-every", type=int, default=4)
    res.add_argument("--check-every", type=int, default=1)
    res.add_argument("--max-retries", type=int, default=3)
    res.add_argument("--events-json", default="")
    res.add_argument("--chaos-particle-loss", type=int, default=0,
                     metavar="STEP", help="NaN particle records of "
                     "shard 0 after STEP (ParticleLoss)")
    res.add_argument("--chaos-particle-count", type=int, default=2)
    res.add_argument("--chaos-nan", type=int, default=0, metavar="STEP")
    res.add_argument("--chaos-save-fail", type=int, default=0,
                     metavar="STEP")
    res.add_argument("--chaos-seed", type=int, default=0)
    args = ap.parse_args()
    if args.resilient and args.json_out:
        ap.error("--json-out records the timed bench path; it is not "
                 "produced by --resilient (use --events-json there)")
    apply_device_flags(args)
    dtype = dtype_from_args(args)

    import jax

    from stencil_tpu.models.pic import Pic
    from stencil_tpu.parallel.mesh import default_mesh_shape

    ndev = len(jax.devices())
    mesh_shape = default_mesh_shape(ndev)
    gx, gy, gz = (args.x * mesh_shape.x, args.y * mesh_shape.y,
                  args.z * mesh_shape.z)
    n = args.particles * ndev
    p = Pic(gx, gy, gz, n, mesh_shape=mesh_shape, dtype=dtype,
            methods=methods_from_args(args),
            capacity=args.capacity or None, budget=args.budget or None,
            deposition=args.deposition, dt=args.dt, seed=args.seed)

    if args.resilient:
        _run_resilient(p, args)
        return

    samples = max(args.iters // args.batch, 1)
    steps_run = 0

    def one():
        nonlocal steps_run
        p.run(args.batch)
        steps_run += args.batch

    # sampled_steps_per_s also runs warmup calls of one(): steps_run
    # counts what actually advanced, so the step counter is honest
    stats, sps = sampled_steps_per_s(one, p.block, samples, args.batch)
    mig = p.migration_stats()
    step_s = 1.0 / sps
    psps = n * sps  # particle steps advanced per second
    print(csv_line("pic", methods_from_args(args), ndev, gx, gy, gz,
                   n, args.deposition,
                   f"{stats.min() / args.batch:.6e}",
                   f"{step_s:.6e}", f"{psps:.6e}",
                   mig["migration_bytes_per_shard"],
                   int(p.overflow_total())))
    p._export_run_metrics(steps_run)
    rec = {
        "bench": "pic",
        "config": {"grid": [gx, gy, gz], "devices": ndev,
                   "particles": n, "deposition": args.deposition,
                   "dt": args.dt, "capacity": p.capacity,
                   "budget": p.budget,
                   "dtype": str(p._dtype)},
        "seconds_per_step": step_s,
        "particle_steps_per_s": psps,
        "migration_bytes_per_shard":
            mig["migration_bytes_per_shard"],
        "overflow": p.overflow_total(),
        "total_charge": p.total_charge(),
    }
    # link-class provenance: the full PIC wire bill (accumulate
    # adjoint + exchange + migration ring) classified per (axis,
    # link_class) — rides the ledger record as config.link_classes
    from stencil_tpu.models.pic import PARTICLE_FIELDS, RADIUS
    from stencil_tpu.observatory.linkmap import classify, pic_traffic
    from stencil_tpu.geometry import Radius
    from stencil_tpu.parallel.mesh import mesh_dim
    counts = mesh_dim(p.dd.mesh)
    local = p.dd.local_size
    tm = pic_traffic((local.z, local.y, local.x),
                     Radius.constant(RADIUS), counts,
                     p._dtype.itemsize, len(PARTICLE_FIELDS), p.budget)
    if tm.edges:
        summary = classify(tm).to_record()
        rec["link_classes"] = {
            k: {"bytes_per_step": v["bytes"], "share": v["share"]}
            for k, v in summary["links"].items()}
    if args.fuse_segments:
        # megastep race on ONE device at the per-device size (the one
        # shared protocol — _common.megastep_race): stepwise = one
        # step + one probe dispatch per iteration, fused = one
        # megastep per k steps with the overflow-carrying probe trace
        # in-graph. The record lands its own pic.megastep ledger
        # trajectory (CI gates presence + positivity here and the
        # trajectory via `observatory gate --min-groups`; the >= 1.5
        # dispatch gate lives on the Jacobi leg — the fake-CPU mesh is
        # not dispatch-bound for PIC's op-count-heavy step).
        from _common import megastep_race

        k = max(args.fuse_check_every, 1)
        nr = max(args.iters, k)
        nr -= nr % k
        dev1 = jax.devices()[:1]

        def mk():
            return Pic(args.x, args.y, args.z, args.particles,
                       mesh_shape=(1, 1, 1), devices=dev1,
                       dtype=dtype, deposition=args.deposition,
                       dt=args.dt, seed=args.seed)

        sps, fps, ratio = megastep_race(
            mk, lambda e: e.make_sentinel(), lambda e: e.state, k, nr)
        rec["fused"] = {
            "check_every": k, "steps": nr,
            "stepwise_steps_per_s": sps,
            "fused_steps_per_s": fps,
            "fused_over_stepwise": ratio,
        }
        print(csv_line("pic-megastep", k, nr, f"{sps:.3f}",
                       f"{fps:.3f}", f"{ratio:.3f}"))
    emit_bench_artifacts(args, rec, "pic")
    if args.metrics_json:
        # one number, two artifacts: the SAME figures as the JSON
        # record land in a telemetry metrics snapshot (the CI
        # bench-metrics parity gate covers this gauge exactly like
        # stencil_bench_steps_per_s{exchange_every})
        from stencil_tpu.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        reg.gauge("stencil_bench_particle_steps_per_s",
                  "measured particle steps/s of the fused PIC loop, "
                  "by deposition scheme"
                  ).set(psps, deposition=args.deposition)
        reg.gauge("stencil_bench_migration_bytes_per_shard",
                  "static migration wire B/shard/step of the measured "
                  "configuration (analytic model, HLO-cross-checked)"
                  ).set(mig["migration_bytes_per_shard"],
                        deposition=args.deposition)
        reg.write_snapshot(args.metrics_json)


if __name__ == "__main__":
    main()
