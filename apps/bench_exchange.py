#!/usr/bin/env python
"""Halo-exchange micro-benchmark with face/edge/corner radius control.

Reference parity: bin/bench_exchange.cu — ``--fr/--er/--cr`` radius
flags, reports trimean seconds and trimean B/s
(bin/bench_exchange.cu:58-64,86-100).

Temporal blocking: ``--exchange-every 1,4`` sweeps communication-
avoiding depths. Each depth is measured two ways: (a) the classic
per-exchange timing on a domain built with ``set_exchange_every(s)``
(deep slabs), and (b) an honest steps/s of the REAL blocked hot path —
``Jacobi3D(exchange_every=s)``'s fused run loop, which pays the
redundant ring compute and the deeper slabs that blocking actually
costs. The amortized byte model (the same source the static analyzer
cross-checks against HLO) is printed next to the measured numbers;
``--json-out`` archives the comparison (the CI bench-smoke artifact).

Only ``csv_line`` rows go to stdout (scripts/run_campaign.sh captures
stdout as the CSV artifact); the sweep commentary goes to stderr.
"""

import argparse
import sys

from _common import (add_bench_record_flags, add_device_flags,
                     add_method_flags, apply_device_flags, csv_line,
                     emit_bench_artifacts, grouped_steps_per_s,
                     methods_from_args, timed_samples)


def _parse_depths(text: str):
    """Comma list of depths to sweep. Plain integers are uniform
    depths; ``axis=value`` tokens (``z=4,y=1,x=1``) merge into ONE
    per-axis asymmetric candidate appended after the uniform sweep."""
    ints = set()
    axes = {}
    for t in (t.strip() for t in text.split(",")):
        if not t:
            continue
        if "=" in t:
            k, v = t.split("=", 1)
            k = k.strip().lower()
            if k not in ("x", "y", "z"):
                raise SystemExit(f"--exchange-every axis token wants "
                                 f"x=/y=/z=, got {t!r}")
            axes[k] = int(v)
        else:
            ints.add(int(t))
    depths = sorted(ints)
    if axes:
        depths.append(axes)
    bad = any(s < 1 for s in ints) or any(v < 1 for v in axes.values())
    if not depths or bad:
        raise SystemExit(f"--exchange-every wants depths >= 1, got {text!r}")
    return depths


def _depth_max(s) -> int:
    return max(s.values()) if isinstance(s, dict) else int(s)


def _depth_label(s) -> str:
    """Stable config label: uniform depths keep the bare integer (the
    historical trajectory key); per-axis depths read ``x.y.z``."""
    if isinstance(s, dict):
        from stencil_tpu.geometry import normalize_depths
        d = normalize_depths(s)
        return f"{d.x}.{d.y}.{d.z}"
    return str(s)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=128, help="per-device x size")
    ap.add_argument("--y", type=int, default=128)
    ap.add_argument("--z", type=int, default=128)
    ap.add_argument("--fr", type=int, default=2, help="face radius")
    ap.add_argument("--er", type=int, default=2, help="edge radius")
    ap.add_argument("--cr", type=int, default=2, help="corner radius")
    ap.add_argument("--fields", type=int, default=1)
    ap.add_argument("--iters", "-n", type=int, default=30)
    ap.add_argument("--exchange-every", default="1", metavar="S[,S...]",
                    help="temporal-blocking depths to sweep (comma "
                         "list; 1 = the classic per-step exchange; "
                         "axis=value tokens like z=4,y=1,x=1 merge "
                         "into one per-axis asymmetric candidate)")
    ap.add_argument("--wire-layout", default="slab", metavar="L[,L...]",
                    help="halo wire message layouts (comma list of "
                         "slab,irredundant): the first is the sweep's "
                         "layout; each EXTRA layout races per-exchange "
                         "seconds + the blocked Jacobi steps/s against "
                         "the sweep baseline at its smallest depth")
    ap.add_argument("--json-out", default="", metavar="PATH",
                    help="write the steps/s + byte-model comparison "
                         "as a JSON artifact")
    ap.add_argument("--metrics-json", default="", metavar="PATH",
                    help="also record the measured numbers as a "
                         "telemetry metrics snapshot (gauges "
                         "stencil_bench_steps_per_s{exchange_every=}, "
                         "stencil_bench_bytes_per_step_model{...}) so "
                         "BENCH_*.json and the metrics surface agree "
                         "on one figure")
    ap.add_argument("--fuse-segments", action="store_true",
                    help="race megastep execution (ONE fused dispatch "
                         "per --check-every steps, health probe trace "
                         "in-graph; parallel/megastep.py) against the "
                         "per-step dispatch loop on the same Jacobi "
                         "problem")
    ap.add_argument("--check-every", type=int, default=8,
                    help="megastep segment length for --fuse-segments")
    ap.add_argument("--autotune", action="store_true",
                    help="run the exchange autotuner (measured plan, "
                         "stencil_tpu/tuning) and compare tuned vs "
                         "Method.Default steps/s on the real blocked "
                         "Jacobi loop")
    ap.add_argument("--tune-cache", default="", metavar="PATH",
                    help="plan cache file for --autotune (default: "
                         "$STENCIL_TUNE_CACHE or "
                         "~/.cache/stencil_tpu/plans.json)")
    add_method_flags(ap)
    add_device_flags(ap)
    add_bench_record_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax
    import numpy as np

    from stencil_tpu.distributed import DistributedDomain
    from stencil_tpu.geometry import Radius
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.parallel.mesh import default_mesh_shape
    from stencil_tpu.utils.timers import device_sync

    from stencil_tpu.parallel.packing import WIRE_LAYOUTS

    ndev = len(jax.devices())
    mesh_shape = default_mesh_shape(ndev)
    gx, gy, gz = (args.x * mesh_shape.x, args.y * mesh_shape.y,
                  args.z * mesh_shape.z)
    depths = _parse_depths(args.exchange_every)
    layouts = [t.strip() for t in args.wire_layout.split(",") if t.strip()]
    bad = [t for t in layouts if t not in WIRE_LAYOUTS]
    if not layouts or bad:
        raise SystemExit(f"--wire-layout wants a comma list from "
                         f"{WIRE_LAYOUTS}, got {args.wire_layout!r}")
    primary_layout = layouts[0]

    def jacobi_steps_per_s(methods, s, layout=primary_layout):
        """Honest steps/s of the REAL blocked hot path: the Jacobi
        model's fused run loop (deep exchange + sub-steps incl. the
        redundant ring compute) under the given configuration, measured
        by the one shared warmup/measure/block contract
        (``_common.grouped_steps_per_s``)."""
        j = Jacobi3D(gx, gy, gz, mesh_shape=mesh_shape,
                     dtype=np.float32, kernel="xla", methods=methods,
                     exchange_every=s if _depth_max(s) > 1 else None,
                     wire_layout=layout)
        j.init()
        n, dt, sps = grouped_steps_per_s(j.run, j.block, args.iters,
                                         group=_depth_max(s))
        return n, dt, sps, j

    def make_domain(layout=primary_layout, s=1):
        dd = DistributedDomain(gx, gy, gz)
        dd.set_mesh_shape(mesh_shape)
        dd.set_radius(Radius.face_edge_corner(args.fr, args.er, args.cr))
        dd.set_methods(methods_from_args(args))
        dd.set_wire_layout(layout)
        if _depth_max(s) > 1:
            dd.set_exchange_every(s)
        for i in range(args.fields):
            dd.add_data(f"q{i}", np.float32)
        dd.realize()
        return dd

    results = []
    link_classes = None  # baseline depth's classified link map
    for s in depths:
        dd = make_domain(s=s)

        # per-exchange timing (the classic bench line, now per config)
        stats = timed_samples(dd.exchange, lambda: device_sync(dd.curr),
                              args.iters)
        per_ex = dd.exchange_bytes_total()
        tm = stats.trimean()
        print(csv_line("bench_exchange", dd.methods, ndev,
                       args.x, args.y, args.z, args.fr, args.er, args.cr,
                       args.fields, _depth_label(s), per_ex,
                       f"{tm:.6e}", f"{per_ex / tm:.6e}"))

        # honest steps/s: the REAL blocked hot path (deep exchange +
        # fused sub-steps incl. the redundant ring compute), via the
        # Jacobi model's radius-1 run loop on the same grid
        n, dt, _, j = jacobi_steps_per_s(methods_from_args(args), s)
        xs = j.exchange_stats()
        row_extra = {}
        if isinstance(s, dict):
            d = j.dd.exchange_depths
            row_extra["depths"] = [d.x, d.y, d.z]
        results.append({
            "exchange_every": (s if isinstance(s, int)
                               else _depth_label(s)),
            **row_extra,
            "steps": n,
            "seconds": dt,
            "steps_per_s": n / dt,
            "exchange_rounds_per_step": xs["rounds_per_iteration"],
            "bytes_per_exchange_model": per_ex,
            "amortized_bytes_per_step_model":
                dd.exchange_bytes_amortized_per_step(),
            "jacobi_bytes_per_step_model": xs["bytes_per_iteration"],
            "trimean_exchange_s": tm,
        })
        print(f"bench_exchange steps: s={_depth_label(s)} "
              f"steps/s={n / dt:.3f} (jacobi blocked loop) "
              f"rounds/step={xs['rounds_per_iteration']:.3f} "
              f"amortized={dd.exchange_bytes_amortized_per_step():.0f}"
              f"B/step (model)", file=sys.stderr)

        if s == depths[0]:
            # link observatory: classify the baseline configuration's
            # modeled traffic matrix against the deployed device order
            # and pair it with the measured per-exchange seconds —
            # per-link B/step + achieved/fitted-peak utilization (the
            # ROADMAP item 3 placement signal, live on every bench)
            from stencil_tpu.observatory.linkmap import \
                link_attribution_for
            link = link_attribution_for(dd)
            if link is not None:
                # ONE derived block feeds all three surfaces (the
                # JSON payload, the metrics gauges, the ledger
                # stamp): utilization = the link's B/s during the
                # measured exchange round over its fitted peak
                total = sum(link["bytes_per_step"].values()) or 1.0
                link_classes = {
                    f"{axis}/{klass}": {
                        "bytes_per_step": b,
                        "share": b / total,
                        "utilization": (b * _depth_max(s) / tm)
                        / link["peak_bytes_per_s"].get(axis, 1e30),
                    }
                    for (axis, klass), b
                    in sorted(link["bytes_per_step"].items())}

    layout_cmp = None
    if len(layouts) > 1:
        # wire-layout race: each extra layout re-runs the smallest
        # swept depth's two measurements (per-exchange seconds on the
        # domain, blocked Jacobi steps/s on the real hot path) and is
        # reported as a ratio against the sweep baseline in results[0].
        # Bytes come from the SAME per-layout model the static analyzer
        # pins against HLO, so the bytes ratio is exact, not sampled.
        base = results[0]
        s0 = depths[0]
        layout_cmp = {"baseline_layout": primary_layout,
                      "exchange_every": s0, "races": {}}
        for layout in layouts[1:]:
            dd = make_domain(layout=layout, s=s0)
            stats = timed_samples(dd.exchange,
                                  lambda: device_sync(dd.curr),
                                  args.iters)
            tm = stats.trimean()
            per_ex = dd.exchange_bytes_total()
            n, dt, sps, _ = jacobi_steps_per_s(
                methods_from_args(args), s0, layout=layout)
            bytes_ratio = (per_ex
                           / (base["bytes_per_exchange_model"] or 1))
            sps_ratio = sps / base["steps_per_s"]
            layout_cmp["races"][layout] = {
                "bytes_per_exchange_model": per_ex,
                "bytes_ratio": bytes_ratio,
                "trimean_exchange_s": tm,
                "exchange_s_ratio": tm / base["trimean_exchange_s"],
                "steps_per_s": sps,
                "steps_per_s_ratio": sps_ratio,
            }
            print(csv_line("bench_exchange_layout", layout,
                           primary_layout, s0, per_ex,
                           f"{tm:.6e}", f"{bytes_ratio:.4f}",
                           f"{sps_ratio:.3f}"))
            print(f"bench_exchange layout: {layout} "
                  f"{per_ex}B/exchange "
                  f"({bytes_ratio:.3f}x {primary_layout} bytes) "
                  f"{sps:.3f} steps/s "
                  f"(x{sps_ratio:.2f} blocked loop)",
                  file=sys.stderr)

    autotune_cmp = None
    if args.autotune:
        # tune for the Jacobi hot-path problem itself (radius 1, one
        # f32 field — the configuration the steps/s claim is about),
        # then race the MEASURED plan against the static Method.Default
        # on the real blocked loop
        from stencil_tpu.distributed import DistributedDomain
        from stencil_tpu.parallel.methods import Method
        from stencil_tpu.utils.profiling import autotune_report

        dd = DistributedDomain(gx, gy, gz)
        dd.set_mesh_shape(mesh_shape)
        dd.set_radius(1)
        dd.add_data("temp", np.float32)
        plan = dd.autotune(cache_path=args.tune_cache or None)
        print(autotune_report(plan), file=sys.stderr)

        # reuse the sweep's s=1 row as the baseline when it already
        # measured exactly Method.Default (no method flags, depth 1
        # swept) instead of re-compiling the same configuration
        base_row = next(
            (r for r in results if r["exchange_every"] == 1
             and methods_from_args(args) == Method.Default), None)
        if base_row is not None:
            base_sps = base_row["steps_per_s"]
        else:
            _, _, base_sps, _ = jacobi_steps_per_s(Method.Default, 1)
        tuned_m = Method[plan.config.method]
        _, _, tuned_sps, _ = jacobi_steps_per_s(
            tuned_m, plan.config.exchange_every)
        autotune_cmp = {
            "plan": plan.to_record(),
            "default_steps_per_s": base_sps,
            "tuned_steps_per_s": tuned_sps,
            "tuned_over_default": tuned_sps / base_sps,
        }
        print(csv_line("bench_exchange_autotune", plan.config.key(),
                       plan.provenance, f"{base_sps:.3f}",
                       f"{tuned_sps:.3f}",
                       f"{tuned_sps / base_sps:.3f}"))
        print(f"bench_exchange autotune: tuned {plan.config.key()} "
              f"({plan.provenance}) {tuned_sps:.3f} steps/s vs default "
              f"{base_sps:.3f} steps/s "
              f"(x{tuned_sps / base_sps:.2f})", file=sys.stderr)

    fused_cmp = None
    if args.fuse_segments:
        # fused megastep vs the per-step dispatch loop the megastep
        # replaced (resilience/driver.py's stepwise mode at
        # check_every=1): one jitted STEP dispatch + one health-probe
        # dispatch per Python iteration on the baseline side, ONE
        # fused dispatch per k steps with the same per-step probes
        # riding in-graph on the megastep side. Same problem, same
        # health coverage — only the host/device boundary moves. The
        # race runs the per-device smoke size on ONE device: that is
        # the dispatch-bound regime the megastep targets (on the
        # multi-threaded fake CPU mesh, in-program thread sync — which
        # fusion cannot remove — swamps the dispatch signal). Three
        # legs, one per newly-fused carry contract: XLA Jacobi, the
        # full PIC state (particle lanes + overflow column in-graph),
        # and Astaroth's temporal path (w carry under lcm(3, s)
        # grouping) — the trajectory for the latter two was empty
        # before the segment compiler.
        from _common import megastep_race

        k = max(args.check_every, 1)
        n = max(args.iters, k)
        n -= n % k
        dev1 = jax.devices()[:1]

        from stencil_tpu.models.astaroth import Astaroth
        from stencil_tpu.models.pic import Pic
        from stencil_tpu.resilience.health import HealthSentinel

        def leg(name, make_engine, make_sentinel, fields_fn, **extra):
            sps, fps, ratio = megastep_race(make_engine, make_sentinel,
                                            fields_fn, k, n)
            row = {"check_every": k, "steps": n,
                   "stepwise_steps_per_s": sps,
                   "fused_steps_per_s": fps,
                   "fused_over_stepwise": ratio, **extra}
            print(csv_line(f"bench_exchange_megastep_{name}", k, n,
                           f"{sps:.3f}", f"{fps:.3f}",
                           f"{ratio:.3f}"))
            print(f"bench_exchange megastep[{name}]: fused[k={k}] "
                  f"{fps:.3f} steps/s vs per-step dispatch "
                  f"{sps:.3f} steps/s (x{ratio:.2f})",
                  file=sys.stderr)
            return row

        def mk_jacobi():
            j = Jacobi3D(args.x, args.y, args.z, mesh_shape=(1, 1, 1),
                         devices=dev1, dtype=np.float32, kernel="xla",
                         methods=methods_from_args(args))
            j.init()
            return j

        def mk_pic():
            # a dispatch-bound particle count: enough to exercise the
            # full deposit/gather/migrate step, small enough that the
            # host round-trip (not compute) sets stepwise steps/s
            return Pic(args.x, args.y, args.z, 256,
                       mesh_shape=(1, 1, 1), devices=dev1,
                       dtype=np.float32, deposition="cic")

        ast_s = 2

        def mk_astaroth():
            a = Astaroth(args.x, args.y, args.z, mesh_shape=(1, 1, 1),
                         devices=dev1, dtype=np.float32, kernel="xla",
                         exchange_every=ast_s)
            a.init()
            return a

        fused_cmp = leg("jacobi", mk_jacobi,
                        lambda e: HealthSentinel(e.dd),
                        lambda e: e.dd.curr)
        fused_cmp["pic"] = leg("pic", mk_pic,
                               lambda e: e.make_sentinel(),
                               lambda e: e.state)
        fused_cmp["astaroth_temporal"] = leg(
            "astaroth", mk_astaroth, lambda e: HealthSentinel(e.dd),
            lambda e: e.dd.curr, exchange_every=ast_s)
        # keep the legacy CSV row shape for dashboards parsing it
        print(csv_line("bench_exchange_megastep", k, n,
                       f"{fused_cmp['stepwise_steps_per_s']:.3f}",
                       f"{fused_cmp['fused_steps_per_s']:.3f}",
                       f"{fused_cmp['fused_over_stepwise']:.3f}"))

    if args.json_out:
        base = results[0]
        results_by_s = {str(r["exchange_every"]): r for r in results}
        comparison = {
            "bench": "bench_exchange",
            "mesh": list(mesh_shape),
            "per_device_size": [args.x, args.y, args.z],
            "radius": [args.fr, args.er, args.cr],
            "fields": args.fields,
            "configs": results,
            # headline ratios vs the smallest swept depth (pass 1 in
            # --exchange-every for a true per-step-exchange baseline):
            # exchange rounds per step drop exactly s-fold; amortized
            # bytes stay ~flat (the deep slabs repay the skipped
            # rounds); steps/s includes the redundant ring compute
            "baseline_exchange_every": base["exchange_every"],
            "rounds_per_step_ratio": {
                k: r["exchange_rounds_per_step"]
                / base["exchange_rounds_per_step"]
                for k, r in results_by_s.items()},
            "steps_per_s_ratio": {
                k: r["steps_per_s"] / base["steps_per_s"]
                for k, r in results_by_s.items()},
            # the halo message geometry the whole sweep rode — the
            # ledger stamps this into config (post-fingerprint) so
            # observatory queries can split slab vs irredundant runs
            "wire_layout": primary_layout,
        }
        if layout_cmp is not None:
            comparison["wire_layout_race"] = layout_cmp
        if autotune_cmp is not None:
            comparison["autotune"] = autotune_cmp
        if fused_cmp is not None:
            comparison["fused"] = fused_cmp
        if link_classes is not None:
            # per-(axis, link_class) byte shares + utilization — the
            # SAME derived block lands in this JSON, the metrics
            # snapshot below, and (as config.link_classes provenance)
            # the ledger record
            comparison["link_classes"] = link_classes
        # one payload, two artifacts: the legacy JSON plus the
        # observatory ledger records derived from it (same converter
        # the backfill CLI runs on the committed BENCH_*.json history)
        emit_bench_artifacts(args, comparison, "bench_exchange")
        print(f"bench_exchange: wrote {args.json_out}", file=sys.stderr)

    if args.metrics_json:
        # one number, two artifacts: the SAME steps/s measured above
        # lands in a telemetry metrics snapshot, so dashboards scraped
        # from the metrics surface and the committed BENCH_*.json can
        # never disagree
        from stencil_tpu.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        g_sps = reg.gauge("stencil_bench_steps_per_s",
                          "measured steps/s of the blocked Jacobi "
                          "loop, by temporal depth")
        g_bps = reg.gauge("stencil_bench_bytes_per_step_model",
                          "amortized exchange B/step (analytic model, "
                          "HLO-cross-checked)")
        for r in results:
            s_label = str(r["exchange_every"])
            g_sps.set(r["steps_per_s"], exchange_every=s_label)
            g_bps.set(r["amortized_bytes_per_step_model"],
                      exchange_every=s_label)
        if autotune_cmp is not None:
            g_tuned = reg.gauge("stencil_bench_tuned_steps_per_s",
                                "steps/s of the measured tuned plan "
                                "vs Method.Default")
            g_tuned.set(autotune_cmp["tuned_steps_per_s"],
                        config="tuned")
            g_tuned.set(autotune_cmp["default_steps_per_s"],
                        config="default")
        if fused_cmp is not None:
            g_fused = reg.gauge(
                "stencil_bench_fused_steps_per_s",
                "megastep race: steps/s by dispatch mode (fused = "
                "one program per check_every steps incl. the "
                "in-graph probe trace; stepwise = one step + one "
                "probe dispatch per step)")
            ck = str(fused_cmp["check_every"])
            g_fused.set(fused_cmp["fused_steps_per_s"],
                        mode="fused", check_every=ck)
            g_fused.set(fused_cmp["stepwise_steps_per_s"],
                        mode="stepwise", check_every=ck)
        if link_classes is not None:
            # the link observatory's two gauges, set from the SAME
            # derived block the JSON pins (CI asserts exact equality
            # between the two surfaces)
            from stencil_tpu.observatory.linkmap import (
                METRIC_LINK_BYTES_PER_STEP, METRIC_LINK_UTILIZATION)
            g_lb = reg.gauge(METRIC_LINK_BYTES_PER_STEP,
                             "modeled wire B/step per mesh axis and "
                             "link class (observatory/linkmap.py)")
            g_lu = reg.gauge(METRIC_LINK_UTILIZATION,
                             "achieved/fitted-peak utilization per "
                             "mesh axis and link class")
            for key, row in link_classes.items():
                axis, klass = key.split("/")
                g_lb.set(row["bytes_per_step"], axis=axis,
                         link_class=klass)
                g_lu.set(row["utilization"], axis=axis,
                         link_class=klass)
        reg.write_snapshot(args.metrics_json)
        print(f"bench_exchange: metrics snapshot -> "
              f"{args.metrics_json}", file=sys.stderr)


if __name__ == "__main__":
    main()
