#!/usr/bin/env python
"""Halo-exchange micro-benchmark with face/edge/corner radius control.

Reference parity: bin/bench_exchange.cu — ``--fr/--er/--cr`` radius
flags, reports trimean seconds and trimean B/s
(bin/bench_exchange.cu:58-64,86-100).
"""

import argparse

from _common import (add_device_flags, apply_device_flags,
                     add_method_flags, csv_line, methods_from_args,
                     timed_samples)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=128, help="per-device x size")
    ap.add_argument("--y", type=int, default=128)
    ap.add_argument("--z", type=int, default=128)
    ap.add_argument("--fr", type=int, default=2, help="face radius")
    ap.add_argument("--er", type=int, default=2, help="edge radius")
    ap.add_argument("--cr", type=int, default=2, help="corner radius")
    ap.add_argument("--fields", type=int, default=1)
    ap.add_argument("--iters", "-n", type=int, default=30)
    add_method_flags(ap)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax
    import numpy as np

    from stencil_tpu.distributed import DistributedDomain
    from stencil_tpu.geometry import Radius
    from stencil_tpu.parallel.mesh import default_mesh_shape
    from stencil_tpu.utils.timers import device_sync

    ndev = len(jax.devices())
    mesh_shape = default_mesh_shape(ndev)
    dd = DistributedDomain(args.x * mesh_shape.x, args.y * mesh_shape.y,
                           args.z * mesh_shape.z)
    dd.set_mesh_shape(mesh_shape)
    dd.set_radius(Radius.face_edge_corner(args.fr, args.er, args.cr))
    dd.set_methods(methods_from_args(args))
    for i in range(args.fields):
        dd.add_data(f"q{i}", np.float32)
    dd.realize()

    stats = timed_samples(dd.exchange, lambda: device_sync(dd.curr),
                          args.iters)
    total = dd.exchange_bytes_total()
    tm = stats.trimean()
    print(csv_line("bench_exchange", dd.methods, ndev,
                   args.x, args.y, args.z, args.fr, args.er, args.cr,
                   args.fields, total,
                   f"{tm:.6e}", f"{total / tm:.6e}"))


if __name__ == "__main__":
    main()
