#!/usr/bin/env python
"""Print the machine/mesh model: processes, devices, torus coords,
memory — the analog of bin/machine_info.cu (nodes, ranks, GPUs by
UUID via the Machine model, reference: include/stencil/machine.hpp)."""

import argparse

from _common import add_device_flags, apply_device_flags


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    add_device_flags(ap)
    apply_device_flags(ap.parse_args())

    import jax

    from stencil_tpu.parallel.mesh import default_mesh_shape, make_mesh

    print(f"process {jax.process_index()} of {jax.process_count()}")
    devs = jax.devices()
    print(f"devices: {len(devs)} (local: {len(jax.local_devices())})")
    for d in devs:
        coords = getattr(d, "coords", None)
        core = getattr(d, "core_on_chip", None)
        mem = None
        try:
            stats = d.memory_stats()
            if stats:
                mem = f"{stats.get('bytes_limit', 0) / 2**30:.1f}GiB"
        except Exception:
            pass
        print(f"  [{d.id}] {d.device_kind} platform={d.platform} "
              f"process={d.process_index} coords={coords} core={core} "
              f"mem={mem}")
    shape = default_mesh_shape(len(devs))
    mesh = make_mesh(shape)
    print(f"default 3D mesh: {tuple(shape)} axes {mesh.axis_names}")


if __name__ == "__main__":
    main()
