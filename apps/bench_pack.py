#!/usr/bin/env python
"""Pack/unpack throughput per direction and size.

Reference parity: bin/bench_pack.cu — DevicePacker/Unpacker throughput
by direction/size. Here the packer analog is the packed-slab path of
the exchange engine: extract + flatten + concatenate the halo slabs of
all quantities for one axis side, then scatter back.
"""

import argparse
import time

from _common import add_device_flags, apply_device_flags, csv_line


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", type=int, nargs="+",
                    default=[32, 64, 128, 256])
    ap.add_argument("--radius", type=int, default=2)
    ap.add_argument("--fields", type=int, default=4)
    ap.add_argument("--iters", "-n", type=int, default=20)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.local_domain import raw_size, zyx_shape
    from stencil_tpu.numerics import Statistics
    from stencil_tpu.utils.timers import device_sync

    r = args.radius

    for n in args.sizes:
        sz = Dim3(n, n, n)
        radius = Radius.constant(r)
        shape = zyx_shape(raw_size(sz, radius))
        arrs = {f"q{i}": jnp.zeros(shape, jnp.float32) + i
                for i in range(args.fields)}

        # pack: slabs of every field on the +x side -> one flat buffer
        def pack(fields):
            slabs = []
            for k in sorted(fields):
                a = fields[k]
                slab = lax.slice_in_dim(a, r, 2 * r, axis=2)
                slabs.append(slab.reshape(-1))
            return jnp.concatenate(slabs)

        # unpack: scatter the buffer back into the halo regions
        def unpack(fields, buf):
            out = {}
            off = 0
            for k in sorted(fields):
                a = fields[k]
                cnt = a.shape[0] * a.shape[1] * r
                slab = lax.dynamic_slice_in_dim(buf, off, cnt).reshape(
                    a.shape[0], a.shape[1], r)
                off += cnt
                out[k] = lax.dynamic_update_slice_in_dim(
                    a, slab, a.shape[2] - r, axis=2)
            return out

        roundtrip = jax.jit(lambda f: unpack(f, pack(f)))
        out = roundtrip(arrs)
        device_sync(out)
        stats = Statistics()
        for _ in range(args.iters):
            t0 = time.perf_counter()
            out = roundtrip(arrs)
            device_sync(out)
            stats.insert(time.perf_counter() - t0)
        nbytes = sum(int(v.shape[0]) * int(v.shape[1]) * r * 4
                     for v in arrs.values()) * 2  # pack + unpack
        tm = stats.trimean()
        print(csv_line("bench_pack", n, r, args.fields, nbytes,
                       f"{tm:.6e}", f"{nbytes / tm:.6e}"))


if __name__ == "__main__":
    main()
