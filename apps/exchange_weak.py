#!/usr/bin/env python
"""Pure exchange() time, weak scaling (per-device size fixed)
(reference: bin/exchange_weak.cu: "measure purely total exchange time")."""

import argparse

from _common import (add_device_flags, apply_device_flags,
                     add_method_flags, csv_line, methods_from_args,
                     timed_samples)


def run_exchange_bench(name: str, gx: int, gy: int, gz: int, mesh_shape,
                       radius: int, fields: int, iters: int, methods) -> None:
    import numpy as np

    from stencil_tpu.distributed import DistributedDomain
    from stencil_tpu.utils.timers import device_sync

    dd = DistributedDomain(gx, gy, gz)
    if mesh_shape is not None:
        dd.set_mesh_shape(mesh_shape)
    dd.set_radius(radius)
    dd.set_methods(methods)
    for i in range(fields):
        dd.add_data(f"q{i}", np.float32)
    dd.realize()
    stats = timed_samples(dd.exchange, lambda: device_sync(dd.curr), iters)
    ndev = dd.placement.dim().flatten()
    total = dd.exchange_bytes_total()
    tm = stats.trimean()
    print(csv_line(name, dd.methods, ndev, gx, gy, gz, radius, fields,
                   total, f"{stats.min():.6e}", f"{tm:.6e}",
                   f"{(total / tm if tm else 0):.6e}"))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=256, help="per-device x size")
    ap.add_argument("--y", type=int, default=256)
    ap.add_argument("--z", type=int, default=256)
    ap.add_argument("--radius", type=int, default=3)
    ap.add_argument("--fields", type=int, default=1)
    ap.add_argument("--iters", "-n", type=int, default=30)
    add_method_flags(ap)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax

    from stencil_tpu.parallel.mesh import default_mesh_shape

    mesh_shape = default_mesh_shape(len(jax.devices()))
    run_exchange_bench("exchange_weak",
                       args.x * mesh_shape.x, args.y * mesh_shape.y,
                       args.z * mesh_shape.z, mesh_shape, args.radius,
                       args.fields, args.iters, methods_from_args(args))


if __name__ == "__main__":
    main()
