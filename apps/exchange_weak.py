#!/usr/bin/env python
"""Pure exchange() time, weak scaling (per-device size fixed)
(reference: bin/exchange_weak.cu: "measure purely total exchange time")."""

import argparse

from _common import (add_device_flags, apply_device_flags,
                     add_method_flags, csv_line, methods_from_args,
                     timed_samples)


def run_exchange_bench(name: str, gx: int, gy: int, gz: int, mesh_shape,
                       radius: int, fields: int, iters: int, methods,
                       interior_slabs: bool = False) -> None:
    import numpy as np

    from stencil_tpu.distributed import DistributedDomain
    from stencil_tpu.utils.timers import device_sync

    if interior_slabs:
        # the fused fast paths' transfer, standalone: interior-resident
        # slab rounds (exchange_interior_slabs) with the SAME byte
        # accounting the models report (interior_slab_bytes), so this
        # bench and Jacobi3D/Astaroth.exchange_stats agree by
        # construction. Needs an x-unsharded mesh (the fast-path
        # contract). No DistributedDomain: the padded orchestrator
        # arrays would only inflate peak memory at exactly the large
        # weak-scaled sizes this bench targets — the timer allocates
        # its own sharded interior-resident zeros.
        from stencil_tpu.geometry import Dim3
        from stencil_tpu.parallel.exchange import (
            interior_slab_bytes, measure_slab_exchange_seconds)
        from stencil_tpu.parallel.mesh import make_mesh, mesh_dim

        mesh = make_mesh(mesh_shape)
        counts = mesh_dim(mesh)
        ndev = counts.flatten()
        if counts.x != 1:
            raise SystemExit("--interior-slabs needs an x-unsharded "
                             "mesh (the fused halo-path contract)")
        if gx % counts.x or gy % counts.y or gz % counts.z:
            raise SystemExit("--interior-slabs needs an evenly "
                             "divisible grid")
        local = Dim3(gx // counts.x, gy // counts.y, gz // counts.z)
        # slab buffers are block-aligned (8-row tiles); radii beyond
        # one tile scale both buffer dims
        buf = max(8, -(-radius // 8) * 8)
        if radius > min(local.z, local.y):
            raise SystemExit(f"--radius {radius} exceeds the local "
                             f"shard {local}")
        sec = measure_slab_exchange_seconds(
            mesh, local, np.float32, rz=buf, ry=buf,
            radius_rows=radius, y_z_extended=True, nfields=fields,
            reps=iters)
        total = interior_slab_bytes(
            (local.z, local.y, local.x), counts, radius, 4,
            y_z_extended=True) * ndev * fields
        print(csv_line(name + "_slabs", "InteriorSlabs", ndev, gx, gy,
                       gz, radius, fields, total, f"{sec:.6e}",
                       f"{sec:.6e}", f"{(total / sec if sec else 0):.6e}"))
        return
    dd = DistributedDomain(gx, gy, gz)
    if mesh_shape is not None:
        dd.set_mesh_shape(mesh_shape)
    dd.set_radius(radius)
    dd.set_methods(methods)
    for i in range(fields):
        dd.add_data(f"q{i}", np.float32)
    dd.realize()
    ndev = dd.placement.dim().flatten()
    stats = timed_samples(dd.exchange, lambda: device_sync(dd.curr), iters)
    total = dd.exchange_bytes_total()
    tm = stats.trimean()
    print(csv_line(name, dd.methods, ndev, gx, gy, gz, radius, fields,
                   total, f"{stats.min():.6e}", f"{tm:.6e}",
                   f"{(total / tm if tm else 0):.6e}"))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--x", type=int, default=256, help="per-device x size")
    ap.add_argument("--y", type=int, default=256)
    ap.add_argument("--z", type=int, default=256)
    ap.add_argument("--radius", type=int, default=3)
    ap.add_argument("--fields", type=int, default=1)
    ap.add_argument("--iters", "-n", type=int, default=30)
    ap.add_argument("--interior-slabs", action="store_true",
                    help="measure the fused fast paths' interior-"
                         "resident slab exchange instead of the padded "
                         "orchestrator exchange (x-unsharded mesh)")
    add_method_flags(ap)
    add_device_flags(ap)
    args = ap.parse_args()
    apply_device_flags(args)

    import jax

    from stencil_tpu.parallel.mesh import (default_mesh_shape,
                                           default_mesh_shape_xfree)

    ndev = len(jax.devices())
    mesh_shape = (default_mesh_shape_xfree(ndev) if args.interior_slabs
                  else default_mesh_shape(ndev))
    run_exchange_bench("exchange_weak",
                       args.x * mesh_shape.x, args.y * mesh_shape.y,
                       args.z * mesh_shape.z, mesh_shape, args.radius,
                       args.fields, args.iters, methods_from_args(args),
                       interior_slabs=args.interior_slabs)


if __name__ == "__main__":
    main()
