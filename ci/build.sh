#!/usr/bin/env bash
# Build the native components from source (the ci/build.sh analog of the
# reference: ci/build.sh + test/CMakeLists.txt:13-50). Today that is the
# QAP placement solver; the script fails if the native path is
# unavailable rather than silently falling back to pure Python.
set -euo pipefail
cd "$(dirname "$0")/.."

mkdir -p stencil_tpu/_build
g++ -O2 -shared -fPIC -std=c++17 \
    stencil_tpu/csrc/qap.cpp -o stencil_tpu/_build/libstencil_qap.so

python - <<'EOF'
from stencil_tpu import qap
assert qap.native_available(), "native QAP solver failed to load"
import numpy as np
w = np.array([[0.0, 2.0], [2.0, 0.0]])
d = np.array([[0.0, 1.0], [1.0, 0.0]])
f, cost = qap.solve(w, d)
assert sorted(f) == [0, 1] and cost == 4.0, (f, cost)
print("native QAP solver OK")
EOF
