#!/usr/bin/env bash
# The full CI pipeline, runnable locally or from the workflow config
# (the .travis.yml:1-20 analog): native build, unit tests on the
# 8-device virtual CPU mesh, app smoke runs, and the multi-chip
# certification sweep. No TPU required.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/4 native build =="
bash ci/build.sh

echo "== 2/4 unit tests (8-device virtual CPU mesh) =="
python -m pytest tests/ -q --maxfail=1

echo "== 3/4 app smoke runs =="
smoke() { echo "-- $*"; python "$@" > /dev/null; }
( cd apps
  smoke jacobi3d.py --x 8 --y 8 --z 8 --iters 2 --batch 1 --fake-cpu 8
  smoke jacobi3d.py --x 8 --y 8 --z 8 --iters 2 --batch 1 --fake-cpu 8 \
        --packed
  smoke jacobi3d.py --x 8 --y 8 --z 8 --iters 2 --batch 1 --fake-cpu 8 \
        --fake-slices 2 --dcn-axis z
  smoke astaroth.py --nx 8 --ny 8 --nz 8 --iters 1 --fake-cpu 8
  smoke bench_exchange.py --x 8 --y 8 --z 8 --iters 2 --fake-cpu 8
  smoke machine_info.py --fake-cpu 8
  smoke bench_qap.py --sizes 4 6
)

echo "== 4/4 multi-chip certification sweep =="
python __graft_entry__.py 8 | tail -1

echo "CI PASSED"
