#!/usr/bin/env bash
# The CI pipeline, runnable locally or from a trigger (the
# .travis.yml:1-20 analog): static lint gate, native build, unit tests
# on the 8-device virtual CPU mesh, app smoke runs, and the multi-chip
# certification sweep. No TPU required.
#
# Tiers (CI_TIER env): "smoke" (default) skips the @pytest.mark.slow
# interpret-mode parity tests and finishes in a few minutes — the
# pre-push / per-commit tier; "full" runs the entire suite (~15 min) —
# the nightly/merge tier.
#
# Lint stage ("lint" job marker): smoke runs stencil-lint + ruff only
# (seconds); full also runs mypy. ruff/mypy are optional dev deps
# (pyproject.toml [project.optional-dependencies].lint) — absent, they
# are skipped with a notice; stencil-lint is part of the tree and
# always gates.
#
# Triggers that invoke this script:
#   * .github/workflows/ci.yml  — push/PR (smoke) + nightly cron (full)
#   * scripts/install_hooks.sh  — local git pre-push hook (smoke)
#   * manual: CI_TIER=full bash ci/run_ci.sh
set -euo pipefail
cd "$(dirname "$0")/.."
TIER="${CI_TIER:-smoke}"

echo "== 1/13 lint (stencil-lint + ruff; tier=$TIER) =="
# stencil-lint: all thirteen static checkers — halo-radius footprint,
# DMA discipline, ppermute sanity, HLO collective-permute-only
# lowering, analytic-vs-HLO byte cross-check, the Pallas VMEM/tiling
# audit, the dataflow trio (donation aliasing, host-transfer hygiene,
# recompile-hazard fingerprints), the prescriptive block-shape tiling
# gate (every Pallas kernel at 256^3/512^3-per-device shapes against
# the PHYSICAL VMEM budget — trace-only, no TPU), the link
# observatory's traffic-matrix-vs-HLO exactness gate, the RDMA
# schedule certifier (happens-before under k-fold replay), and the
# precision certifier (dtype-flow proofs gating low-precision wire
# formats)
# (python -m stencil_tpu.analysis, see README "Static analysis").
# The hlo/costmodel byte checks capability-gate themselves on the
# image's JAX (StableHLO lowering support is probed; Pallas targets
# skip off-TPU with a note in the report) — no env detection needed
# here. Exits nonzero on findings; the JSON report is the CI artifact
# (archived to $CI_ARTIFACT_DIR when a trigger provides one).
# capture the exit code so the report is archived even (especially)
# when the lint stage fails — red CI with no artifact helps no one
lint_rc=0
python -m stencil_tpu.analysis --json stencil_lint_report.json \
  || lint_rc=$?
if [ -n "${CI_ARTIFACT_DIR:-}" ] && [ -f stencil_lint_report.json ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp stencil_lint_report.json "$CI_ARTIFACT_DIR/"
fi
if [ "$lint_rc" -ne 0 ]; then
  echo "stencil-lint failed (exit $lint_rc)"
  exit "$lint_rc"
fi
# the prescriptive tiling PLAN report (ranked legal block shapes /
# named binding constraints for every registered Pallas kernel at the
# production per-device shapes) — a CI artifact for real-TPU runs to
# pick their shapes from; the audit itself already gated above
python -m stencil_tpu.analysis --plan-tiling 'analysis.tiling.*' \
  --json stencil_tiling_plans.json > /dev/null
if [ -n "${CI_ARTIFACT_DIR:-}" ] && [ -f stencil_tiling_plans.json ]; then
  cp stencil_tiling_plans.json "$CI_ARTIFACT_DIR/"
fi
# the RDMA schedule certificates (analysis/schedule.py): the per-kernel
# happens-before verdicts megastep's fusion gate consumes. Archived
# next to the tiling plans; then the fused⇒certified invariant — every
# registry target megastep fuses (fused_by_megastep) MUST hold a
# replay_safe certificate this run, and at least one such target must
# exist (a deregistered fused target would otherwise pass vacuously).
python -m stencil_tpu.analysis -q --only 'analysis.schedule.*' \
  --json stencil_schedule_certificates.json > /dev/null
if [ -n "${CI_ARTIFACT_DIR:-}" ] && \
   [ -f stencil_schedule_certificates.json ]; then
  cp stencil_schedule_certificates.json "$CI_ARTIFACT_DIR/"
fi
python - stencil_schedule_certificates.json <<'EOF'
import json
import sys
d = json.load(open(sys.argv[1]))
fused = {k: v for k, v in d["metrics"].items()
         if k.startswith("schedule:") and v.get("fused_by_megastep")}
assert fused, "no fused-by-megastep schedule targets registered"
bad = [k for k, v in fused.items() if not v.get("replay_safe")]
assert not bad, \
    f"megastep fuses UNCERTIFIED RDMA schedules: {bad} — every fused " \
    f"kernel must hold a replay_safe certificate (analysis/schedule.py)"
print(f"schedule certificates OK: {len(fused)} fused target(s), all "
      f"replay_safe")
EOF
# the precision certificates (analysis/precision.py): the per-target
# dtype-flow verdicts the wire-format gate consumes. Archived next to
# the schedule certificates; then the realized⇒certified invariant —
# every declared-narrowing wire target in the registry MUST hold a
# safe certificate with zero silent converts this run (and at least
# one such target must exist, or dropping the bf16 registry entries
# would pass vacuously), and every target of checker 13 must certify
# safe — the same certificates make_exchange's realize()-time gate
# re-derives before it lets a narrow wire ship.
python -m stencil_tpu.analysis -q --only precision \
  --json precision_certificates.json > /dev/null
if [ -n "${CI_ARTIFACT_DIR:-}" ] && [ -f precision_certificates.json ]
then
  cp precision_certificates.json "$CI_ARTIFACT_DIR/"
fi
python - precision_certificates.json <<'EOF'
import json
import sys
d = json.load(open(sys.argv[1]))
certs = {k: v for k, v in d["metrics"].items()
         if k.startswith("precision:")}
assert len(certs) >= 13, f"precision coverage shrank: {sorted(certs)}"
unsafe = [k for k, v in certs.items() if not v.get("safe")]
assert not unsafe, \
    f"UNCERTIFIED precision targets: {unsafe} — every registered " \
    f"entry point must hold a safe PrecisionCertificate " \
    f"(analysis/precision.py)"
leaky = [k for k, v in certs.items() if v.get("silent_converts")]
assert not leaky, f"silent converts in shipped paths: {leaky}"
wired = {k: v for k, v in certs.items() if any(
    rec.get("declared") not in (None, "f32")
    for rec in v.get("wire_dtypes", {}).values())}
assert wired, "no declared-narrowing wire targets registered"
for k, v in wired.items():
    assert v["max_rel_error_bound"] > 0, (k, v)
# the irredundant wire layout must hold its own safe certificates —
# the layout reroutes every halo byte through the packed-box pack/
# unpack path, and dropping its registry entries would let a dtype
# regression in that path ship unproven
irr = [k for k, v in certs.items()
       if "layout=irredundant" in k and v.get("safe")]
assert irr, "no safe irredundant-layout precision certificate " \
    "registered (make_exchange[...,layout=irredundant])"
fp8 = [k for k, v in certs.items() if "wire=e4m3" in k and v.get("safe")]
assert fp8, "no safe fp8 wire certificate registered"
print(f"precision certificates OK: {len(certs)} target(s) all safe, "
      f"{len(wired)} narrow-wire declaration(s) certified, "
      f"{len(irr)} irredundant-layout, {len(fp8)} fp8")
EOF
# the pack-layout report (parallel/packing.py): slab-vs-irredundant
# modeled wire bytes for the canonical exchange configs — the numbers
# the registry's CostModel targets just pinned HLO-exactly above,
# archived standalone next to the precision certificates so TPU runs
# can read the expected savings without re-deriving the model
python - > pack_layout_report.json <<'EOF'
import json
from stencil_tpu.parallel.packing import pack_layout_report
rep = pack_layout_report()
assert rep and all(r["irredundant_bytes"] < r["slab_bytes"]
                   for r in rep.values()), rep
json.dump(rep, __import__("sys").stdout, indent=1)
EOF
if [ -n "${CI_ARTIFACT_DIR:-}" ] && [ -f pack_layout_report.json ]; then
  cp pack_layout_report.json "$CI_ARTIFACT_DIR/"
fi
# the link observatory artifact: the modeled per-link traffic matrix
# (whose per-method totals the linkmap checker just pinned HLO-exactly
# above) plus the placement-quality report — both the QAP hill-climb
# AND the placement make_placement(mode="auto") actually DEPLOYS (the
# new default: QAP on non-uniform fabrics, trivial on uniform ones)
# must not lose to trivial placement on any registered mesh (ROADMAP
# item 3's gate, exit nonzero on failure)
python -m stencil_tpu.observatory linkmap --placement-report \
  --json stencil_linkmap.json > /dev/null
if [ -n "${CI_ARTIFACT_DIR:-}" ] && [ -f stencil_linkmap.json ]; then
  cp stencil_linkmap.json "$CI_ARTIFACT_DIR/"
fi
# registry-count ratchet: audit coverage may only grow. A refactor
# that drops targets (deregisters an entry point, deletes a checker
# block) must bump ci/registry_floor.txt EXPLICITLY in review — it
# cannot shrink the gate silently.
python - stencil_lint_report.json ci/registry_floor.txt <<'EOF'
import json
import sys
n = json.load(open(sys.argv[1]))["counts"]["targets"]
floor = int(open(sys.argv[2]).read().split()[0])
assert n >= floor, \
    f"registry shrank: {n} targets < committed floor {floor} " \
    f"(ci/registry_floor.txt) — audit coverage silently dropped"
print(f"registry ratchet OK: {n} targets >= committed floor {floor}")
EOF
if python -c "import ruff" 2>/dev/null; then
  python -m ruff check stencil_tpu/
elif command -v ruff >/dev/null; then
  ruff check stencil_tpu/
else
  echo "-- ruff not installed; skipping (pip install .[lint] to enable)"
fi
if [ "$TIER" = "full" ]; then
  if python -c "import mypy" 2>/dev/null; then
    python -m mypy stencil_tpu/
  elif command -v mypy >/dev/null; then
    mypy stencil_tpu/
  else
    echo "-- mypy not installed; skipping (pip install .[lint] to enable)"
  fi
fi

echo "== 2/13 native build =="
bash ci/build.sh

echo "== 3/13 unit tests, tier=$TIER (8-device virtual CPU mesh) =="
# The full tier is dominated by interpret-mode Pallas parity tests
# (CPU-bound, independent): fan them out with pytest-xdist when the
# machine has cores to spare. Each worker process builds its own
# 8-virtual-device CPU mesh (conftest env), so workers don't interact.
NP=$(nproc 2>/dev/null || echo 1)
XDIST=()
if [ "$NP" -ge 4 ] && python -c "import xdist" 2>/dev/null; then
  XDIST=(-n "$((NP / 2))")
fi
if [ "$TIER" = "full" ]; then
  python -m pytest tests/ -q --maxfail=1 "${XDIST[@]+"${XDIST[@]}"}"
else
  python -m pytest tests/ -q --maxfail=1 -m "not slow"
fi

echo "== 4/13 app smoke runs =="
# overlap app smokes execute remote DMA: possible only on a TPU or
# with the distributed (mosaic) interpreter — probe, don't assume
RDMA_OK=$(python -c "from stencil_tpu._compat import remote_dma_runnable
print(1 if remote_dma_runnable() else 0)")
smoke() { echo "-- $*"; python "$@" > /dev/null; }
( cd apps
  smoke jacobi3d.py --x 8 --y 8 --z 8 --iters 2 --batch 1 --fake-cpu 8
  smoke jacobi3d.py --x 8 --y 8 --z 8 --iters 2 --batch 1 --fake-cpu 8 \
        --packed
  smoke jacobi3d.py --x 8 --y 8 --z 8 --iters 2 --batch 1 --fake-cpu 8 \
        --fake-slices 2 --dcn-axis z
  smoke astaroth.py --nx 8 --ny 8 --nz 8 --iters 1 --fake-cpu 8
  if [ "$RDMA_OK" = "1" ]; then
    smoke astaroth.py --nx 8 --ny 8 --nz 8 --iters 1 --fake-cpu 4 \
          --kernel halo --overlap
  else
    echo "-- SKIP astaroth --overlap smoke (no interpreted remote DMA" \
         "in this JAX; stencil-lint covers the kernels statically)"
  fi
  smoke bench_exchange.py --x 8 --y 8 --z 8 --iters 2 --fake-cpu 8
  smoke machine_info.py --fake-cpu 8
  smoke bench_qap.py --sizes 4 6
)

echo "== 5/13 bench smoke: temporal blocking + autotuned plan =="
# communication-avoiding temporal blocking must not regress steps/s of
# the REAL blocked hot path (Jacobi3D's fused run loop, redundant ring
# compute included) on the fake CPU mesh; the amortized byte model
# (cross-checked against HLO by stencil-lint's costmodel checker) is
# archived next to the measured numbers. --autotune additionally races
# the MEASURED plan against Method.Default on the same loop. The JSON
# pins the exchange-rounds-per-step 4x cut and both steps/s
# comparisons; it is written to a scratch path (the committed
# BENCH_pr4.json records the PR-time numbers and must not churn on
# every CI run) and archived to $CI_ARTIFACT_DIR when a trigger
# provides one.
BENCH_JSON="$(mktemp -t BENCH_pr4.XXXXXX.json)"
BENCH_METRICS="$(mktemp -t BENCH_metrics.XXXXXX.json)"
TUNE_CACHE="$(mktemp -t tune_cache.XXXXXX.json)"; rm -f "$TUNE_CACHE"
# scratch observatory ledger: the bench (here) and pic (stage 8) smoke
# runs append their versioned records to it; the observatory stage (9)
# validates it, gates it, and proves a synthetic regression fails
OBS_LEDGER="$(mktemp -t obs_ledger.XXXXXX.jsonl)"; rm -f "$OBS_LEDGER"
# the exchange-every sweep carries the per-axis asymmetric leg
# (z=4,y=1,x=1: deep temporal blocking on z only — the DCN-crossing
# axis on hierarchical fabrics — while x/y refresh every step); its
# record must land in the ledger with the config.depths stamp the
# observatory keys asymmetric trajectories by
( cd apps
  STENCIL_BENCH_LEDGER="$OBS_LEDGER" \
  python bench_exchange.py --x 8 --y 8 --z 8 --iters 20 --fake-cpu 8 \
        --exchange-every 1,4,z=4,y=1,x=1 --autotune \
        --tune-cache "$TUNE_CACHE" \
        --fuse-segments --check-every 8 \
        --wire-layout slab,irredundant \
        --json-out "$BENCH_JSON" --metrics-json "$BENCH_METRICS" )
BENCH_JSON="$BENCH_JSON" BENCH_METRICS="$BENCH_METRICS" \
OBS_LEDGER="$OBS_LEDGER" python - <<'EOF'
import json
import os
d = json.load(open(os.environ["BENCH_JSON"]))
# telemetry parity: the metrics snapshot records the SAME steps/s the
# BENCH json pins — one number, two artifacts, no drift
from stencil_tpu.telemetry import snapshot_value
snap = json.load(open(os.environ["BENCH_METRICS"]))
for cfg in d["configs"]:
    s = str(cfg["exchange_every"])
    got = snapshot_value(snap, "stencil_bench_steps_per_s",
                         exchange_every=s)
    assert got == cfg["steps_per_s"], (s, got, cfg["steps_per_s"])
rounds = d["rounds_per_step_ratio"]
speed = d["steps_per_s_ratio"]
assert abs(rounds["4"] - 0.25) < 1e-9, rounds
# steps/s of the blocked loop must not regress beyond run-to-run noise
assert speed["4"] > 0.8, speed
# the MEASURED tuned plan must not lose to the static default beyond
# noise (the committed BENCH_pr4.json pins the PR-time tuned >= default)
at = d["autotune"]
assert at["plan"]["provenance"] in ("tuned", "cached"), at["plan"]
assert at["tuned_over_default"] > 0.8, at
# megastep gate: ONE fused dispatch per check_every steps must beat the
# per-step dispatch loop >= 1.5x at the dispatch-bound smoke size
# (committed BENCH_pr8.json pins the PR-time numbers; this re-measures)
fz = d["fused"]
assert fz["fused_over_stepwise"] >= 1.5, fz
# the newly fused carry contracts' race legs must exist and land
# their measured records (their ledger trajectories were empty before
# the segment compiler; stage 9 gates the trajectories). On the
# fake-CPU mesh these two paths are NOT dispatch-bound — PIC's step
# is ~100s of tiny XLA ops and the temporal path's minimal legal
# shard (deep radius 6 on an 8-point axis) balloons the redundant
# deep-window compute, neither of which fusion can remove — so the
# >= 1.5 dispatch gate stays on the Jacobi leg where the dispatch
# signal is clean; the carry-contract legs gate presence + positive
# measurements here and their own regression trajectory in stage 9
# (the 1.5x expectation for them is a real-TPU figure, where device
# steps are ~us against ~100us host dispatches).
for leg in ("pic", "astaroth_temporal"):
    sub = d["fused"][leg]
    assert sub["fused_steps_per_s"] > 0, (leg, sub)
    assert sub["stepwise_steps_per_s"] > 0, (leg, sub)
    assert sub["steps"] >= d["fused"]["check_every"], (leg, sub)
ck = str(fz["check_every"])
for mode, key in (("fused", "fused_steps_per_s"),
                  ("stepwise", "stepwise_steps_per_s")):
    got = snapshot_value(snap, "stencil_bench_fused_steps_per_s",
                         mode=mode, check_every=ck)
    assert got == fz[key], (mode, got, fz[key])
# link observatory parity: the two per-link gauges record the SAME
# figures the JSON's link_classes block pins — and the classes must
# actually partition the traffic (shares sum to 1)
lc = d.get("link_classes")
assert lc, "bench payload carries no link_classes block"
assert abs(sum(v["share"] for v in lc.values()) - 1.0) < 1e-9, lc
for key, v in lc.items():
    axis, klass = key.split("/")
    got = snapshot_value(snap, "stencil_link_bytes_per_step",
                         axis=axis, link_class=klass)
    assert got == v["bytes_per_step"], (key, got, v)
    got = snapshot_value(snap, "stencil_link_utilization_ratio",
                         axis=axis, link_class=klass)
    assert got == v["utilization"], (key, got, v)
    assert 0 < v["utilization"] < 1, (key, v)
# wire-layout race: the irredundant leg must move strictly fewer
# modeled bytes than the slab baseline (the static analyzer pinned the
# exact figures against HLO in stage 1; here the measured race must
# exist and agree with the model's direction), and the ledger record
# this run appended must carry the layout provenance stamp
assert d["wire_layout"] == "slab", d["wire_layout"]
race = d["wire_layout_race"]["races"]["irredundant"]
assert 0 < race["bytes_ratio"] < 1, race
assert race["steps_per_s"] > 0, race
# asymmetric-depth leg: the z=4,y=1,x=1 config must exist in the
# sweep with its per-axis depths surfaced, and its ledger record must
# carry the config.depths stamp (stamped post-fingerprint so uniform
# trajectories never fork; the observatory groups asym runs by it)
asym = [c for c in d["configs"] if c["exchange_every"] == "1.1.4"]
assert asym and asym[0].get("depths") == [1, 1, 4], d["configs"]
assert asym[0]["steps_per_s"] > 0, asym
led = [json.loads(l) for l in open(os.environ["OBS_LEDGER"])
       if l.strip()]
mine = [r for r in led if r.get("bench") == "bench_exchange"]
assert mine and mine[-1]["config"].get("wire_layout") == "slab", \
    "ledger record missing config.wire_layout stamp"
led_asym = [r for r in mine
            if r["config"].get("exchange_every") == "1.1.4"]
assert led_asym and led_asym[-1]["config"].get("depths") == [1, 1, 4], \
    "asymmetric-depth ledger record missing config.depths stamp"
print(f"bench smoke OK: rounds/step x{1/rounds['4']:.0f} fewer, "
      f"steps/s ratio {speed['4']:.2f}, tuned/default "
      f"x{at['tuned_over_default']:.2f} "
      f"({at['plan']['config']['method']}"
      f"[s={at['plan']['config']['exchange_every']}]), "
      f"megastep fused/stepwise x{fz['fused_over_stepwise']:.2f} "
      f"[k={ck}]")
EOF
if [ -n "${CI_ARTIFACT_DIR:-}" ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp "$BENCH_JSON" "$CI_ARTIFACT_DIR/BENCH_pr4.json"
  cp "$BENCH_JSON" "$CI_ARTIFACT_DIR/BENCH_pr8.json"
  cp "$BENCH_METRICS" "$CI_ARTIFACT_DIR/bench_metrics.json"
  # the megastep ratio, archived standalone for trend dashboards
  python - "$BENCH_JSON" > "$CI_ARTIFACT_DIR/megastep_ratio.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
json.dump(d["fused"], sys.stdout, indent=1)
EOF
fi
rm -f "$BENCH_JSON" "$BENCH_METRICS" "$TUNE_CACHE"
# NOTE: "$OBS_LEDGER" survives into stages 8/9 (the observatory stage)

echo "== 6/13 exchange autotuner (fake timer: search/fit/plan/cache) =="
# the tuner's whole pipeline with deterministic fake measurements (no
# hardware dependence): first invocation tunes and writes the plan
# cache, the second MUST be a cache hit performing zero measurements.
# The plan JSON is the CI artifact.
TUNE_CACHE="$(mktemp -t tune_cache.XXXXXX.json)"; rm -f "$TUNE_CACHE"
PLAN1="$(mktemp -t tune_plan1.XXXXXX.json)"
PLAN2="$(mktemp -t tune_plan2.XXXXXX.json)"
python -m stencil_tpu.tune --x 64 --y 64 --z 64 --fields 2 --fake-cpu 8 \
  --fake-timer --cache "$TUNE_CACHE" --json "$PLAN1"
python -m stencil_tpu.tune --x 64 --y 64 --z 64 --fields 2 --fake-cpu 8 \
  --fake-timer --cache "$TUNE_CACHE" --json "$PLAN2"
PLAN1="$PLAN1" PLAN2="$PLAN2" python - <<'EOF'
import json
import os
p1 = json.load(open(os.environ["PLAN1"]))
p2 = json.load(open(os.environ["PLAN2"]))
assert p1["provenance"] == "tuned" and p1["measurements"] > 0, p1
assert p2["provenance"] == "cached" and p2["measurements"] == 0, p2
assert p1["config"] == p2["config"], (p1["config"], p2["config"])
print(f"autotune smoke OK: {p1['config']['method']}"
      f"[s={p1['config']['exchange_every']}] tuned with "
      f"{p1['measurements']} measurements; second run cache hit "
      f"with 0")
EOF
if [ -n "${CI_ARTIFACT_DIR:-}" ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp "$PLAN1" "$CI_ARTIFACT_DIR/tuned_plan.json"
fi
rm -f "$TUNE_CACHE" "$PLAN1" "$PLAN2"

echo "== 7/13 chaos smoke: resilient run loop under injected faults =="
# the Jacobi app under run_resilient (stencil_tpu/resilience) with a
# seeded fault plan: one NaN injection (must trip the health sentinel
# and roll back to the last good checkpoint) and one transient save
# IOError (must be retried with backoff, not kill the run). The run
# must COMPLETE all iterations with >= 1 rollback and >= 1 save retry
# recorded; the resilience event log JSON is the CI artifact.
# The fused dispatch runs under jax.transfer_guard("disallow") (the
# driver wires it; STENCIL_ALLOW_TRANSFERS=1 is the escape hatch) and
# under the recompile watchdog (STENCIL_ASSERT_SINGLE_COMPILE=1 set
# here): an implicit host transfer or a re-traced megastep inside the
# hot loop fails this stage loudly.
CHAOS_CKPT="$(mktemp -d -t chaos_ckpt.XXXXXX)"
CHAOS_EVENTS="$(mktemp -t chaos_events.XXXXXX.json)"
CHAOS_FLIGHT="$(mktemp -d -t chaos_flight.XXXXXX)"
( cd apps
  STENCIL_ASSERT_SINGLE_COMPILE=1 \
  STENCIL_FLIGHT_RECORDER_DIR="$CHAOS_FLIGHT" \
  python jacobi3d.py --x 8 --y 8 --z 8 --iters 12 --fake-cpu 8 \
        --resilient --fuse-segments --ckpt-dir "$CHAOS_CKPT" \
        --ckpt-every 4 --check-every 1 --chaos-nan 6 \
        --chaos-save-fail 4 --events-json "$CHAOS_EVENTS" )
CHAOS_EVENTS="$CHAOS_EVENTS" python - <<'EOF'
import json
import os
d = json.load(open(os.environ["CHAOS_EVENTS"]))
assert d["steps"] == 12, d
assert d["rollbacks"] >= 1, d
assert d["save_retries"] >= 1, d
assert not d["preempted"], d
# the run went through the FUSED megastep driver (a silent stepwise
# fallback now shows up as fused: false + a fused_decline event)
assert d["fused"] is True, d
kinds = [e["event"] for e in d["events"]]
assert "sentinel_tripped" in kinds and "restored" in kinds, kinds
print(f"chaos smoke OK: {d['steps']} steps completed with "
      f"{d['rollbacks']} rollback(s), {d['save_retries']} save "
      f"retr(ies), final config {d['final_config']}")
EOF
# the resilience report speaks the unified telemetry event schema
python -m stencil_tpu.telemetry validate-events "$CHAOS_EVENTS"
# flight recorder: the injected NaN trip must have produced a schema-
# valid black-box dump whose incident timeline contains the trip AND
# the rollback it resolved into (observatory/recorder.py)
CHAOS_DUMP="$(ls "$CHAOS_FLIGHT"/flight_*sentinel_trip*.json | head -1)"
python -m stencil_tpu.observatory validate "$CHAOS_DUMP"
CHAOS_DUMP="$CHAOS_DUMP" python - <<'EOF'
import os
from stencil_tpu.observatory import render_timeline
tl = render_timeline(os.environ["CHAOS_DUMP"])
assert "sentinel_tripped" in tl, tl
assert "restored" in tl, tl
print("chaos flight dump OK: timeline carries the trip + rollback "
      f"({len(tl.splitlines())} timeline rows)")
EOF
if [ -n "${CI_ARTIFACT_DIR:-}" ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp "$CHAOS_EVENTS" "$CI_ARTIFACT_DIR/chaos_events.json"
  cp "$CHAOS_DUMP" "$CI_ARTIFACT_DIR/chaos_flight_dump.json"
fi
rm -rf "$CHAOS_CKPT" "$CHAOS_EVENTS" "$CHAOS_FLIGHT"

echo "== 8/13 pic smoke: particle migration + ParticleLoss chaos =="
# the particle-in-cell workload (stencil_tpu/models/pic.py): a short
# run proves the dynamic migration path end-to-end (CSV line, zero
# overflow, charge conserved), then a chaos run injects a ParticleLoss
# fault (NaN'd particle records) that must trip the sentinel via the
# particle lanes, roll back to a checkpoint carrying the lanes as
# extras, and still complete every step. The event log is the CI
# artifact.
PIC_CKPT="$(mktemp -d -t pic_ckpt.XXXXXX)"
PIC_EVENTS="$(mktemp -t pic_events.XXXXXX.json)"
PIC_BENCH="$(mktemp -t pic_bench.XXXXXX.json)"
PIC_METRICS="$(mktemp -t pic_metrics.XXXXXX.json)"
( cd apps
  STENCIL_BENCH_LEDGER="$OBS_LEDGER" \
  python pic.py --x 8 --y 8 --z 8 --particles 64 --iters 4 --batch 2 \
        --fake-cpu 8 --deposition ngp --f64 \
        --json-out "$PIC_BENCH" --metrics-json "$PIC_METRICS" \
        > /dev/null
  # second fingerprint-identical measured run: gives the observatory
  # ledger a genuine same-(fingerprint, bench) TRAJECTORY (two
  # records, one group) so stage 9's gate actually compares something
  # — its --min-groups floor pins that this never silently regresses
  # to a vacuous 0-group pass
  STENCIL_BENCH_LEDGER="$OBS_LEDGER" \
  python pic.py --x 8 --y 8 --z 8 --particles 64 --iters 4 --batch 2 \
        --fake-cpu 8 --deposition ngp --f64 \
        --json-out "$PIC_BENCH.2" > /dev/null
  rm -f "$PIC_BENCH.2"
  # chaos leg runs FUSED by default (the megastep driver is the
  # production path now): ParticleLoss must trip at the exact step
  # from the in-graph trace rows and recover bitwise
  python pic.py --x 8 --y 8 --z 8 --particles 64 --iters 6 --fake-cpu 8 \
        --resilient --fuse-segments --ckpt-dir "$PIC_CKPT" \
        --ckpt-every 2 --check-every 1 --chaos-particle-loss 3 \
        --events-json "$PIC_EVENTS" > /dev/null )
PIC_EVENTS="$PIC_EVENTS" PIC_BENCH="$PIC_BENCH" \
PIC_METRICS="$PIC_METRICS" python - <<'EOF'
import json
import os
b = json.load(open(os.environ["PIC_BENCH"]))
assert b["overflow"] == 0, b
assert b["total_charge"] == b["config"]["particles"], b
assert b["particle_steps_per_s"] > 0, b
# telemetry parity: the metrics snapshot records the SAME figures the
# pic JSON pins — one number, two artifacts, no drift (the same gate
# stage 5 applies to stencil_bench_steps_per_s{exchange_every})
from stencil_tpu.telemetry import snapshot_value
snap = json.load(open(os.environ["PIC_METRICS"]))
dep = b["config"]["deposition"]
got = snapshot_value(snap, "stencil_bench_particle_steps_per_s",
                     deposition=dep)
assert got == b["particle_steps_per_s"], (got, b)
got = snapshot_value(snap, "stencil_bench_migration_bytes_per_shard",
                     deposition=dep)
assert got == b["migration_bytes_per_shard"], (got, b)
# the megastep race (pic.py --fuse-segments, default on): the fused
# dispatch mode must produce a positive measured ratio — its record
# lands the pic.megastep ledger trajectory stage 9 gates (the smoke
# box is not dispatch-bound for PIC's op-count-heavy step, so the
# race is a trajectory signal here, not a 1.5x gate; see stage 5)
fz = b.get("fused")
assert fz, "pic payload carries no fused race block"
assert fz["fused_steps_per_s"] > 0, fz
assert fz["stepwise_steps_per_s"] > 0, fz
d = json.load(open(os.environ["PIC_EVENTS"]))
assert d["steps"] == 6, d
assert d["rollbacks"] >= 1, d
# the chaos run went through the FUSED driver (megastep mode)
assert d["fused"] is True, d
kinds = [e["event"] for e in d["events"]]
assert "fault_particle_loss" in kinds, kinds
assert "sentinel_tripped" in kinds and "restored" in kinds, kinds
trip = [e for e in d["events"] if e["event"] == "sentinel_tripped"][0]
assert trip["step"] == 3, trip
print(f"pic smoke OK: {b['particle_steps_per_s']:.0f} particle "
      f"steps/s, charge conserved, fused chaos driver tripped "
      f"ParticleLoss at step 3 + {d['rollbacks']} rollback(s), "
      f"{d['steps']}/6 steps, megastep race "
      f"x{fz['fused_over_stepwise']:.2f}")
EOF
python -m stencil_tpu.telemetry validate-events "$PIC_EVENTS"
if [ -n "${CI_ARTIFACT_DIR:-}" ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp "$PIC_EVENTS" "$CI_ARTIFACT_DIR/pic_events.json"
  cp "$PIC_BENCH" "$CI_ARTIFACT_DIR/BENCH_pr10.json"
  cp "$PIC_METRICS" "$CI_ARTIFACT_DIR/pic_metrics.json"
  # the pic megastep ratio, archived standalone next to
  # megastep_ratio.json (stage 5) for trend dashboards
  python - "$PIC_BENCH" > "$CI_ARTIFACT_DIR/pic_megastep_ratio.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
json.dump(d["fused"], sys.stdout, indent=1)
EOF
fi
rm -rf "$PIC_CKPT" "$PIC_EVENTS" "$PIC_BENCH" "$PIC_METRICS"

echo "== 9/13 observatory: bench ledger validate/gate + backfill =="
# the bench trajectory ledger (stencil_tpu/observatory/ledger.py): the
# bench (stage 5) and pic (stage 8) smoke runs appended their records
# to the scratch ledger — validate it, prove the regression gate
# passes on the real run, prove an injected synthetic same-fingerprint
# steps/s regression exits NONZERO, and backfill-convert the committed
# legacy BENCH_*.json history (validated + diffed) the way the
# committed bench/ledger.jsonl was seeded.
python -m stencil_tpu.observatory validate "$OBS_LEDGER"
# --min-groups 1: the smoke runs above MUST have produced at least one
# comparable (fingerprint, bench) group — an empty/group-less ledger
# exits 0 with a "no measured trajectory" note in dev, but in CI that
# would be a vacuous pass (benches stopped appending), so the
# committed coverage floor turns it into a loud failure; the verdict
# JSON (groups_checked stamped) is archived with the stage artifacts
OBS_GATE_JSON="$(mktemp -t obs_gate.XXXXXX.json)"
# threshold 0.8: back-to-back 8^3 smoke runs on a shared CI box are
# noisy (compile/thread scheduling) — the gate exists to catch the
# order-of-magnitude class of regression, which the synthetic 10x
# check below proves it does at this threshold
# --min-groups 2: the pic smoke's double run now creates TWO
# comparable trajectory groups — the pic bench itself AND the
# pic.megastep fused/stepwise race (the carry-contract paths' ledger
# trajectories, empty before the segment compiler, are gated here)
python -m stencil_tpu.observatory gate "$OBS_LEDGER" --threshold 0.8 \
  --min-groups 2 --json "$OBS_GATE_JSON"
OBS_BAD="$(mktemp -t obs_bad.XXXXXX.jsonl)"
cp "$OBS_LEDGER" "$OBS_BAD"
OBS_LEDGER="$OBS_LEDGER" OBS_BAD="$OBS_BAD" python - <<'EOF'
import json
import os
# synthetic regression: clone the newest record with steps/s cut 10x —
# the same-(fingerprint, bench) gate must catch it
with open(os.environ["OBS_LEDGER"]) as f:
    rec = json.loads(f.read().splitlines()[-1])
rec["metrics"]["steps_per_s"] /= 10.0
rec["created"] += 1.0
with open(os.environ["OBS_BAD"], "a") as f:
    f.write(json.dumps(rec) + "\n")
EOF
if python -m stencil_tpu.observatory gate "$OBS_BAD" --threshold 0.8; then
  echo "observatory gate FAILED to catch the synthetic regression"
  exit 1
else
  echo "observatory gate correctly rejects the synthetic regression"
fi
OBS_LEGACY="$(mktemp -t obs_legacy.XXXXXX.jsonl)"; rm -f "$OBS_LEGACY"
python -m stencil_tpu.observatory backfill --out "$OBS_LEGACY" \
  BENCH_pr3.json BENCH_pr4.json BENCH_pr8.json BENCH_pr10.json \
  BENCH_r01.json BENCH_r02.json BENCH_r03.json BENCH_r04.json \
  BENCH_r05.json
python -m stencil_tpu.observatory validate "$OBS_LEGACY"
# the live smoke records and their backfilled ancestors share one
# converter, so the bench_exchange trajectory diffs across them. A
# group-less ledger now exits 0 with a note, so grep for an actual
# metric row — a converter regression that forked the trajectory
# groups must fail HERE, not print a polite note and pass
OBS_DIFF_OUT="$(python -m stencil_tpu.observatory diff "$OBS_LEGACY" \
  --bench bench_exchange)"
echo "$OBS_DIFF_OUT"
if ! grep -q "steps_per_s" <<< "$OBS_DIFF_OUT"; then
  echo "observatory diff found no comparable bench_exchange" \
       "trajectory — the backfill converter forked the groups"
  exit 1
fi
# the committed seed ledger stays in sync with the backfill converter
python -m stencil_tpu.observatory validate bench/ledger.jsonl
if [ -n "${CI_ARTIFACT_DIR:-}" ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp "$OBS_LEDGER" "$CI_ARTIFACT_DIR/bench_ledger.jsonl"
  cp "$OBS_LEGACY" "$CI_ARTIFACT_DIR/bench_ledger_legacy.jsonl"
  cp "$OBS_GATE_JSON" "$CI_ARTIFACT_DIR/bench_ledger_gate.json"
fi
rm -f "$OBS_LEDGER" "$OBS_BAD" "$OBS_LEGACY" "$OBS_GATE_JSON"

echo "== 10/13 service smoke: concurrent multi-tenant ensemble campaigns =="
# the campaign service (stencil_tpu/serving) on the fake CPU mesh:
# three concurrent fake tenants share one problem fingerprint and ride
# ONE batched ensemble dispatch stream (tenant0 gets a chaos NaN that
# must roll back ONLY its campaign), then a fingerprint-identical
# second wave must hit the engine cache (zero recompiles) and a second
# PROCESS on the same tune cache must hit the plan cache (zero tuner
# measurements). The event log JSON is the CI artifact.
SERVE_ROOT="$(mktemp -d -t serve_root.XXXXXX)"
SERVE_CACHE="$(mktemp -t serve_cache.XXXXXX.json)"; rm -f "$SERVE_CACHE"
SERVE_EVENTS1="$(mktemp -t serve_events1.XXXXXX.json)"
SERVE_EVENTS2="$(mktemp -t serve_events2.XXXXXX.json)"
( cd apps
  python serve.py --tenants 3 --steps 6 --width 8 --fake-cpu 8 \
        --chaos-nan 3 --fake-timer --tune-cache "$SERVE_CACHE" \
        --root "$SERVE_ROOT/run1" --events-json "$SERVE_EVENTS1"
  python serve.py --tenants 1 --second-wave 0 --steps 4 --width 8 \
        --fake-cpu 8 --fake-timer --tune-cache "$SERVE_CACHE" \
        --root "$SERVE_ROOT/run2" --events-json "$SERVE_EVENTS2" )
SERVE_EVENTS1="$SERVE_EVENTS1" SERVE_EVENTS2="$SERVE_EVENTS2" \
python - <<'EOF'
import json
import os
d1 = json.load(open(os.environ["SERVE_EVENTS1"]))
d2 = json.load(open(os.environ["SERVE_EVENTS2"]))
s1, s2 = d1["stats"], d2["stats"]
# run 1: 3 concurrent tenants + 1 warm-path request, all complete; the
# chaos NaN rolled back only its campaign
assert s1["completed"] == 4 and s1["failed"] == 0, s1
assert s1["rollbacks"] >= 1, s1
batches = [e for e in d1["events"] if e["event"] == "batch_started"]
assert batches[0]["compiled"] and batches[0]["measurements"] > 0, batches
# the fingerprint-identical second wave: zero recompiles, zero
# measurements (engine cache + in-process plan reuse)
assert not batches[-1]["compiled"], batches
assert batches[-1]["measurements"] == 0, batches
trips = [e for e in d1["events"] if e["event"] == "sentinel_tripped"]
assert trips and all(e["tenant"] == "tenant0" for e in trips), trips
done = {e["tenant"] for e in d1["events"]
        if e["event"] == "campaign_completed"}
assert done == {"tenant0", "tenant1", "tenant2", "tenant3"}, done
# run 2 (fresh process, same tune cache): plan-cache hit, zero
# tuner measurements
assert s2["completed"] == 1 and s2["plan_cache_hits"] == 1, s2
assert s2["tuner_measurements"] == 0, s2
print(f"service smoke OK: {s1['completed']}+{s2['completed']} campaigns"
      f" completed, {s1['rollbacks']} member-isolated rollback(s), "
      f"warm path compiled=False/measurements=0, second process "
      f"plan-cache hit with 0 measurements")
EOF
if [ -n "${CI_ARTIFACT_DIR:-}" ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp "$SERVE_EVENTS1" "$CI_ARTIFACT_DIR/serve_events.json"
fi
rm -rf "$SERVE_ROOT" "$SERVE_CACHE" "$SERVE_EVENTS1" "$SERVE_EVENTS2"

echo "== 11/13 telemetry: metrics surface, span trace, unified events =="
# the observability acceptance gate (stencil_tpu/telemetry): a first
# service process (cold: tunes once) and a second process on the same
# plan cache (warm) each export their metrics snapshot, span trace,
# and unified event log. The warm-path invariants are asserted from
# the EXPORTED metrics — recompiles_total == 0 (the in-process warm
# wave re-used the cached engine) and, in the second process,
# tuner_measurements_total == 0 with plan_cache_hits_total == 1 — not
# from internal fields. The Perfetto trace and both event logs are
# schema-validated by the telemetry CLI and archived.
TM_ROOT="$(mktemp -d -t tm_root.XXXXXX)"
TM_CACHE="$(mktemp -t tm_cache.XXXXXX.json)"; rm -f "$TM_CACHE"
TM_EVENTS1="$(mktemp -t tm_events1.XXXXXX.json)"
TM_EVENTS2="$(mktemp -t tm_events2.XXXXXX.json)"
TM_METRICS1="$(mktemp -t tm_metrics1.XXXXXX.json)"
TM_METRICS2="$(mktemp -t tm_metrics2.XXXXXX.json)"
TM_TRACE="$(mktemp -t tm_trace.XXXXXX.json)"
( cd apps
  python serve.py --tenants 2 --steps 4 --width 8 --fake-cpu 8 \
        --fake-timer --tune-cache "$TM_CACHE" --root "$TM_ROOT/run1" \
        --events-json "$TM_EVENTS1" --metrics-json "$TM_METRICS1" \
        --trace-json "$TM_TRACE"
  python serve.py --tenants 1 --second-wave 0 --steps 4 --width 8 \
        --fake-cpu 8 --fake-timer --tune-cache "$TM_CACHE" \
        --root "$TM_ROOT/run2" --events-json "$TM_EVENTS2" \
        --metrics-json "$TM_METRICS2" )
# the trace loads (Perfetto format) and both event logs are schema-valid
python -m stencil_tpu.telemetry validate-trace "$TM_TRACE"
python -m stencil_tpu.telemetry validate-events "$TM_EVENTS1"
python -m stencil_tpu.telemetry validate-events "$TM_EVENTS2"
TM_METRICS1="$TM_METRICS1" TM_METRICS2="$TM_METRICS2" python - <<'EOF'
import json
import os
from stencil_tpu.telemetry import snapshot_value as v
m1 = json.load(open(os.environ["TM_METRICS1"]))
m2 = json.load(open(os.environ["TM_METRICS2"]))
# the "== 0" gates below must test series that EXIST in the export
# (counters are seeded to 0 at registration) — a renamed or deleted
# metric must fail here, not read back as an absent-series 0.0
for snap, which in ((m1, "cold"), (m2, "warm")):
    for n in ("stencil_service_recompiles_total",
              "stencil_service_tuner_measurements_total"):
        assert snap["metrics"][n]["samples"], f"{n} absent ({which})"
# run 1 (cold + in-process warm wave): one compile, zero REcompiles,
# the warm wave hit the engine cache; the tuner measured exactly once
assert v(m1, "stencil_service_compiles_total") == 1, m1
assert v(m1, "stencil_service_recompiles_total") == 0, m1
assert v(m1, "stencil_service_engine_cache_hits_total") >= 1, m1
assert v(m1, "stencil_service_tuner_measurements_total") > 0, m1
assert v(m1, "stencil_service_campaigns_total",
         tenant="tenant0", outcome="completed") == 1, m1
# run 2 (fresh process, same plan cache): the warm path verbatim —
# zero recompiles, zero tuner measurements, one plan-cache hit
assert v(m2, "stencil_service_recompiles_total") == 0, m2
assert v(m2, "stencil_service_tuner_measurements_total") == 0, m2
assert v(m2, "stencil_service_plan_cache_hits_total") == 1, m2
assert v(m2, "stencil_service_member_steps_total") >= 4, m2
print("telemetry smoke OK: warm path proven from exported metrics "
      "(recompiles=0, tuner_measurements=0, plan_cache_hits=1), "
      "trace + event logs schema-valid")
EOF
if [ -n "${CI_ARTIFACT_DIR:-}" ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp "$TM_METRICS1" "$CI_ARTIFACT_DIR/telemetry_metrics_cold.json"
  cp "$TM_METRICS2" "$CI_ARTIFACT_DIR/telemetry_metrics_warm.json"
  cp "$TM_TRACE" "$CI_ARTIFACT_DIR/telemetry_trace.json"
  cp "$TM_EVENTS1" "$CI_ARTIFACT_DIR/telemetry_events.json"
fi
rm -rf "$TM_ROOT" "$TM_CACHE" "$TM_EVENTS1" "$TM_EVENTS2" \
       "$TM_METRICS1" "$TM_METRICS2" "$TM_TRACE"

echo "== 12/13 fleet chaos smoke: replica kill + admission flood =="
# the zero-loss gate (ROADMAP item 4) proven from EXPORTED surfaces:
# a calm 3-replica / 4-tenant fleet establishes the reference digests,
# then a chaos fleet on the SAME plan cache kills the replica that
# rendezvous-owns tenant t0 mid-batch (member step 2, after that
# step's checkpoints landed) while a priority-0 admission flood
# hammers the front door. Gates: zero campaigns lost (every final
# field digest bitwise-equal to the calm run), recovered campaigns
# RESUMED from a checkpoint (not restarted), survivors'
# recompiles_total and tuner_measurements_total both 0 (shared plan
# cache + bounded engine cache), >= 1 request shed with a NAMED
# reason, the fleet event log schema-valid, and the dead replica's
# flight-recorder black box archived.
FLEET_ROOT="$(mktemp -d -t fleet_root.XXXXXX)"
FLEET_CACHE="$(mktemp -t fleet_cache.XXXXXX.json)"; rm -f "$FLEET_CACHE"
FLEET_CALM="$(mktemp -t fleet_calm.XXXXXX.json)"
FLEET_CHAOS="$(mktemp -t fleet_chaos.XXXXXX.json)"
FLEET_EVENTS="$(mktemp -t fleet_events.XXXXXX.json)"
FLEET_METRICS="$(mktemp -t fleet_metrics.XXXXXX.json)"
FLEET_FLIGHT="$(mktemp -d -t fleet_flight.XXXXXX)"
( cd apps
  python fleet.py --replicas 3 --tenants 4 --steps 6 --fake-cpu 8 \
        --fake-timer --tune-cache "$FLEET_CACHE" \
        --root "$FLEET_ROOT/calm" --results-json "$FLEET_CALM"
  python fleet.py --replicas 3 --tenants 4 --steps 6 --fake-cpu 8 \
        --fake-timer --tune-cache "$FLEET_CACHE" \
        --root "$FLEET_ROOT/chaos" --kill-owner-of t0 \
        --kill-at-step 2 --flood 6 --max-queue-depth 3 \
        --results-json "$FLEET_CHAOS" --events-json "$FLEET_EVENTS" \
        --metrics-json "$FLEET_METRICS" --flight-dir "$FLEET_FLIGHT" )
python -m stencil_tpu.telemetry validate-events "$FLEET_EVENTS"
[ -n "$(ls -A "$FLEET_FLIGHT")" ] \
  || { echo "FAIL: dead replica left no flight-recorder dump"; exit 1; }
FLEET_CALM="$FLEET_CALM" FLEET_CHAOS="$FLEET_CHAOS" \
FLEET_EVENTS="$FLEET_EVENTS" FLEET_METRICS="$FLEET_METRICS" \
python - <<'EOF'
import json
import os
from stencil_tpu.telemetry import snapshot_value as v
calm = json.load(open(os.environ["FLEET_CALM"]))
chaos = json.load(open(os.environ["FLEET_CHAOS"]))
ev = json.load(open(os.environ["FLEET_EVENTS"]))
met = json.load(open(os.environ["FLEET_METRICS"]))
# zero campaigns lost: every tenant finished, bitwise-equal to calm
assert set(chaos["campaigns"]) == set(calm["campaigns"]), chaos
for t, c in chaos["campaigns"].items():
    assert c["ok"], (t, c)
    assert c["digest"] == calm["campaigns"][t]["digest"], t
# the killed replica really died and its campaigns really RESUMED
killed = f"replica-{chaos['killed']}"
states = {n: r["state"] for n, r in chaos["replicas"].items()}
assert states[killed] == "dead", states
assert v(met, "stencil_fleet_replicas", state="dead") == 1, met
assert v(met, "stencil_fleet_replicas", state="active") == 2, met
assert v(met, "stencil_fleet_recovered_campaigns_total") >= 1, met
resumed = [c for c in chaos["campaigns"].values()
           if c.get("resumed_from") is not None]
assert resumed and all(c["resumed_from"] > 0 for c in resumed), chaos
# survivors: zero recompiles, zero tuner measurements — and the
# series EXIST in the export (seeded 0), not absent-series 0.0
for n, r in chaos["replicas"].items():
    if r["state"] != "active":
        continue
    assert r["recompiles"] == 0, (n, r)
    assert r["tuner_measurements"] == 0, (n, r)
    for m in ("stencil_service_recompiles_total",
              "stencil_service_tuner_measurements_total"):
        assert r["metrics"]["metrics"][m]["samples"], (n, m)
# the flood was shed LOUDLY: counter + named-reason events agree
shed = v(met, "stencil_fleet_shed_total",
         tenant="flood", reason="queue_depth")
assert shed >= 1, met
sheds = [e for e in ev["events"] if e["event"] == "request_shed"]
assert len(sheds) == int(shed), (shed, sheds)
assert all(e["reason"] in ("queue_depth", "admission_latency")
           for e in sheds), sheds
kinds = {e["event"] for e in ev["events"]}
assert {"fault_replica_crash", "replica_dead",
        "campaign_recovered"} <= kinds, kinds
n_rec = sum(1 for e in ev["events"]
            if e["event"] == "campaign_recovered")
print(f"fleet chaos smoke OK: {killed} killed mid-batch, "
      f"{n_rec} campaign(s) recovered bitwise-equal, survivors "
      f"recompiles=0 tuner_measurements=0, {int(shed)} request(s) "
      f"shed (queue_depth), events schema-valid")
EOF
if [ -n "${CI_ARTIFACT_DIR:-}" ]; then
  mkdir -p "$CI_ARTIFACT_DIR"
  cp "$FLEET_CALM" "$CI_ARTIFACT_DIR/fleet_calm.json"
  cp "$FLEET_CHAOS" "$CI_ARTIFACT_DIR/fleet_chaos.json"
  cp "$FLEET_EVENTS" "$CI_ARTIFACT_DIR/fleet_events.json"
  cp "$FLEET_METRICS" "$CI_ARTIFACT_DIR/fleet_metrics.json"
  mkdir -p "$CI_ARTIFACT_DIR/fleet_flight"
  cp "$FLEET_FLIGHT"/* "$CI_ARTIFACT_DIR/fleet_flight/" 2>/dev/null || true
fi
rm -rf "$FLEET_ROOT" "$FLEET_CACHE" "$FLEET_CALM" "$FLEET_CHAOS" \
       "$FLEET_EVENTS" "$FLEET_METRICS" "$FLEET_FLIGHT"

echo "== 13/13 multi-chip certification sweep =="
python __graft_entry__.py 8 | tail -1

echo "CI PASSED"
